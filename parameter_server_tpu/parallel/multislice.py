"""Cross-process parameter-server tier: range-sharded servers + slice workers.

Reference analog: the whole N-servers x M-workers topology of the reference
(scheduler assigns ranges, workers Push/Pull against servers over the wire,
src/system/ + src/parameter/shared_parameter.h). On a TPU pod that topology
collapses into one SPMD program (parallel/spmd.py) — THIS module is for the
tier where a single program can't reach: separate processes/slices joined
only by host networking (DCN), and the multi-process integration harness
(the analog of script/local.sh, the reference's de-facto integration test).

Each *server* process owns a contiguous key range of the model (ref:
Range::EvenDivide over servers) and applies the shared updaters
(kv/updaters.py) on push. Each *worker* process streams its assigned file
shards (coordinator workload pool), localizes batches, pulls touched
weights per range, computes the CSR gradient on its local device with the
same jitted math as the single-program path (ops/sparse.py), and pushes
per-range gradients back. Consistency is the coordinator's SSP clock
(`max_delay`), exactly the reference's wait_time dependency.

The reference's message filters come back to life on this wire
(src/filter/): key caching (send a signature instead of the key list when
the server has seen it), zlib compression of payload blocks, and
fixed-point float truncation with stochastic rounding (filters/fixed_point).

Quantized transport (``[wire] quant = int8|int16``, filters/quant.py): a
push's gradient rides as a per-segment-scale integer payload (~3.8x fewer
bytes at int8) with CLIENT-SIDE ERROR FEEDBACK — the residual each
quantized push loses to rounding is folded into the next push of the same
keys, so the server's (stochastically rounded, unbiased) applies converge
to the float trajectory. The feature negotiates per connection (the
``_feat``/"qwire" advert): against a server that never acks, the handle
transparently stays on the float path — and flushes any accumulated
residual into its next float push, so no gradient mass is ever stranded
by a mid-run downgrade. Residual folding happens exactly once per LOGICAL
push, at encode time: transport-level resends and the ``"k<n>"``
keyed-seq recovery path reuse the already-encoded payload, so chaos
(drop/disconnect/duplicate) can never double-fold an accumulator.
``[wire] quant_pull`` extends the codec to pull replies (read-mostly
serving traffic; no feedback loop, so it is opt-in).

Serving plane (``[serve]``, ISSUE 7): production traffic is dominated by
read-mostly pulls from inference, and the OSDI'14 key-cache filter
generalizes to VALUES for it. Every RCU publish stamps the shard with a
monotonic per-life snapshot version; pull replies carry it, and a
serving :class:`ServerHandle` (``serving=True`` + ``[serve] cache``)
caches the decoded rows per key-set signature — serving them locally
within ``ttl_ms``, revalidating with ``if_newer=<ver>`` past it (an
unchanged shard answers ``not_modified`` with zero payload), and
invalidating its own entries exactly on push. Server-side, concurrent
and repeated pulls of a HOT key set against one snapshot share a single
encoded reply (single-flight coalescing), and admission control sheds
revalidations that advertised a cached fallback (``shed_ok``) once the
apply queue or the withheld reply bytes cross the ``[serve] shed_*``
thresholds — bounded staleness for readers instead of unbounded queue
growth for everyone. The training tier never arms the cache: its
staleness contract is the SSP clock, not a TTL.
"""

from __future__ import annotations

import hashlib
import json
import os
import queue as queue_mod
import threading
import time
from collections import OrderedDict
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any

import numpy as np

from parameter_server_tpu.kv import store as kv_store
from parameter_server_tpu.kv.updaters import Updater
from parameter_server_tpu.parallel.chaos import PLAN_ENV, SEED_ENV, FaultPlan
from parameter_server_tpu.parallel.control import (
    Arrays,
    ControlClient,
    Coordinator,
    DeferredReply,
    RpcClient,
    RpcServer,
)
from parameter_server_tpu.utils import flightrec, trace
from parameter_server_tpu.utils.clock import now_wall_us, skew_clamped_age_s
from parameter_server_tpu.utils.config import PSConfig, ServeConfig, ServerConfig
from parameter_server_tpu.utils.flightrec import watchdog
from parameter_server_tpu.utils.heartbeat import HeartbeatReporter, host_stats
from parameter_server_tpu.utils.keyrange import KeyRange
from parameter_server_tpu.utils.metrics import (
    RangeScope,
    key_heat,
    latency_histograms,
    observe_scalar,
    race_track,
    telemetry_snapshot,
    wire_counters,
)


def _plan_from_cfg(cfg: PSConfig) -> FaultPlan | None:
    """FaultPlan from [fault] fault_plan/fault_seed ("" = rely on the
    PS_FAULT_PLAN env fallback inside RpcServer)."""
    if not cfg.fault.fault_plan:
        return None
    return FaultPlan.parse(cfg.fault.fault_plan, seed=cfg.fault.fault_seed)


def _sig(keys: np.ndarray) -> str:
    """Key-list signature (ref: key_caching.h signatures)."""
    return hashlib.blake2b(keys.tobytes(), digest_size=8).hexdigest()


# Bound on cached key lists per endpoint. Streamed minibatches mostly have
# distinct key sets (hits come from pull->push pairs and epoch repeats), so
# an unbounded cache would grow linearly with steps; the need_keys retry
# makes eviction always safe.
_KEY_CACHE_CAP = 512


class _LruSigs:
    """Tiny thread-safe LRU over signature -> value (value may be None for a
    set). Locked: server connection threads and the worker's in-flight push
    threads touch these caches concurrently."""

    def __init__(self, cap: int = _KEY_CACHE_CAP):
        from collections import OrderedDict

        self._d: OrderedDict = OrderedDict()
        self._cap = cap
        self._lock = threading.Lock()

    def get(self, k):
        with self._lock:
            if k in self._d:
                self._d.move_to_end(k)
                return self._d[k]
            return None

    def __contains__(self, k) -> bool:
        with self._lock:
            return k in self._d

    def put(self, k, v=None) -> None:
        with self._lock:
            self._d[k] = v
            self._d.move_to_end(k)
            while len(self._d) > self._cap:
                self._d.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)


class _EncodeEntry:
    """One single-flight encoded pull reply: the first puller of a hot
    key set against a given snapshot computes the encode; concurrent and
    later pulls of the same (signature, version, codec) wait on ``event``
    and reuse the SAME reply header + arrays (``rep is None`` after the
    event fires means the owner's encode failed — followers encode for
    themselves). ``nbytes`` is the payload size counted against the
    cache's byte budget: 0 until filled, and 0 forever if the entry was
    evicted before its owner filled it."""

    __slots__ = ("event", "rep", "arrays", "nbytes")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.rep: dict[str, Any] | None = None
        self.arrays: Arrays | None = None
        self.nbytes = 0


class _QueuedPush:
    """One decoded push waiting in the apply queue: keys + decoded grad,
    its durable dedup identity, the caller's trace context (so the apply
    still joins the client's trace across the thread hop), and the Future
    the deferred RPC reply resolves from."""

    __slots__ = ("keys", "grad", "cid", "seq", "tctx", "future", "t_enq")

    def __init__(
        self, keys: np.ndarray, grad: np.ndarray,
        cid: str | None, seq: str | None,
        tctx: dict | None = None,
    ):
        self.keys = keys
        self.grad = grad
        self.cid = cid
        self.seq = seq
        self.tctx = tctx
        self.future: Future = Future()
        # enqueue mark: the apply thread reports queue-wait vs jitted-
        # apply time back through the deferred reply (_apw_us/_apl_us),
        # the latency-forensics planes' apply-segment split
        self.t_enq = time.perf_counter()


class ShardServer:
    """One server process: updater state over its key range, served via RPC.

    Commands: pull / push / dump / stats / shutdown. State lives on the
    process's default JAX device (CPU in the simulated harness, the local
    chip in a real multi-slice run) and updates run eagerly — this tier is
    wire-bound, not compute-bound.

    Batched apply engine (ref: the paper's servers applying *aggregated*
    updates over touched keys only): pushes don't apply on their serving
    connection threads anymore. Each decoded push lands in a bounded
    queue; ONE dedicated apply thread drains whatever has concurrently
    arrived (up to ``[server] max_batch``), pre-aggregates duplicate keys
    across clients (``kv.store.coalesce_pushes`` — the store's
    exactly-once invariant for nonlinear updaters), applies the updater
    ONCE over the union of touched rows, records the whole batch in the
    durable push ledger atomically with the state it produced, and
    publishes the new state as a single reference swap. Pulls and dumps
    serve from that published snapshot WITHOUT the write lock (RCU: the
    state dict is never mutated after publish, so a reader sees the
    pre- or post-batch table, never a torn mix); SSP bounded-delay
    semantics are unchanged — staleness was always bounded by the clock,
    not by this lock. ``[server] apply_queue = 0`` disables the engine
    (pushes apply inline under the lock — the serial pre-engine
    discipline, kept as the bench baseline).
    """

    def __init__(
        self,
        updater: Updater,
        key_range: KeyRange,
        vdim: int = 1,
        host: str = "127.0.0.1",
        port: int = 0,
        advertise_host: str = "",
        fault_plan: FaultPlan | None = None,
        server_cfg: ServerConfig | None = None,
        serve_cfg: ServeConfig | None = None,
    ):
        import jax.numpy as jnp

        scfg = server_cfg or ServerConfig()
        svcfg = serve_cfg or ServeConfig()
        self.updater = updater
        self.range = key_range
        # versioned RCU publish: (state dict, version) swap as ONE tuple,
        # so a lock-free reader can never see rows stamped with a version
        # they don't belong to. The version is an opaque snapshot id —
        # monotonic within this server life, namespaced by a per-life
        # nonce in the high bits so a cached version from a PREVIOUS life
        # (whose tail pushes a checkpoint restart may have rolled back)
        # can never falsely validate against this one. 23 nonce bits +
        # 40 counter bits stays under 2^63, so ver / if_newer always fit
        # the binary header's fixed unsigned slots (an unmasked nonce
        # overflowed them half the time, silently demoting the serving
        # fields to the JSON tail for that server life).
        self._ver_base = (
            int.from_bytes(os.urandom(3), "big") & ((1 << 23) - 1)
        ) << 40
        # freshness plane (ISSUE 17): the publish timestamp (µs epoch)
        # rides the tuple so the lock-free reader captures (state,
        # version, publish-ts) in ONE reference swap — a pull reply's
        # age is measured against exactly the publish its rows came
        # from, never a neighbour publish.
        self._pub: tuple[dict[str, Any], int, int] = (
            updater.init(key_range.size, vdim), self._ver_base + 1,
            now_wall_us(),
        )
        self._serve_cfg = svcfg
        # freshness plane: this range's traffic/age matrix (per-range
        # counters+hists riding the ordinary telemetry namespaces)
        self._range_scope = RangeScope(key_range.begin, key_range.end)
        # single-flight encoded-pull cache: (sig, version, codec) -> entry
        self._enc_lock = threading.Lock()
        self._enc_cache: OrderedDict[tuple, _EncodeEntry] = OrderedDict()
        self._enc_cap = max(0, int(svcfg.encode_cache_entries))
        self._enc_bytes = 0  # filled entries' payload bytes (LRU-bounded)
        self._enc_bytes_max = max(0, int(svcfg.encode_cache_mb)) << 20
        # hot-key detection: pull counts per key-set signature (advisory
        # — a lost increment under a race only delays hotness by a pull)
        self._hot_counts = _LruSigs(cap=4096)
        # host weights snapshot: (version, full weights table as numpy),
        # materialized lazily on the first HOT pull of a snapshot and
        # shared by every encode at that version — a hot pull is then a
        # numpy fancy-index (~us) instead of an eager jax gather +
        # weights dispatch (~ms). Swapped as one tuple (atomic read);
        # racing materializations of a fresh version duplicate bounded
        # work instead of serializing behind a lock.
        self._host_w: tuple[int, np.ndarray] | None = None
        self._jnp = jnp
        self._key_cache = _LruSigs()  # (worker, sig) -> key array
        self._lock = threading.Lock()
        self._max_batch = max(1, int(scfg.max_batch))
        # adaptive batch ceiling (scfg.adaptive_batch): ramp the drain
        # bound to the observed arrival rate — double while batches fill
        # and the queue stays hot, halve when arrivals go sparse;
        # max_batch stays the hard ceiling
        self._adaptive_batch = bool(scfg.adaptive_batch)
        self._eff_batch = (
            min(4, self._max_batch) if self._adaptive_batch
            else self._max_batch
        )
        self._apply_q: queue_mod.Queue[_QueuedPush] | None = (
            queue_mod.Queue(maxsize=int(scfg.apply_queue))
            if scfg.apply_queue > 0
            else None
        )
        self._apply_open = self._apply_q is not None
        self._apply_thread: threading.Thread | None = None
        self._ctr_lock = threading.Lock()  # counters bumped by conn threads
        self._ckpt_write_lock = threading.Lock()  # one dump writer at a time
        self._ckpt_thread: threading.Thread | None = None
        # durable push dedup: cid -> recently applied push seqs (str-keyed;
        # seqs normalize through str() so the ledger survives the npz
        # round-trip). Mutated ONLY under self._lock, in the same critical
        # section as the state mutation it describes, and checkpointed
        # with the state — the RpcServer reply cache dies with the
        # process, so without this a push applied-and-dumped whose reply
        # was lost to a kill would be re-applied by the restarted server.
        self._applied_push: OrderedDict[str, OrderedDict[str, None]] = OrderedDict()
        self.counters = {
            "pulls": 0, "pushes": 0, "cache_hits": 0, "need_keys": 0,
            "push_replays": 0, "apply_batches": 0, "push_coalesced": 0,
            # serving plane (ISSUE 7): conditional pulls answered without
            # a payload, pulls shed under overload, real row encodes, and
            # encodes shared across pulls by the single-flight cache
            "not_modified": 0, "shed": 0, "pull_encodes": 0,
            "encode_reuse": 0,
        }
        if host in ("0.0.0.0", "::", "") and not advertise_host:
            raise ValueError(
                "binding a wildcard address requires advertise_host: "
                "publishing 0.0.0.0 to the coordinator would point remote "
                "workers at their own loopback"
            )
        self.server = RpcServer(
            self._handle, host, port, fault_plan=fault_plan,
            # pull/dump/stats re-apply harmlessly — bypassing the reply
            # cache keeps their row-payload replies from being pinned
            idempotent_cmds=frozenset({"pull", "dump", "stats"}),
            expose_identity=True,  # push branch keeps the durable ledger
            lane_hi=scfg.lane_hi,
            lane_lo=scfg.lane_lo,
            withheld_max_bytes=scfg.withheld_max_mb << 20,
            # this server decodes the per-segment quantized codec: acking
            # "qwire" is what lets a quantized client leave the float path
            features=frozenset({"qwire"}),
        )
        # bind and advertise may differ: bind 0.0.0.0 to accept remote
        # workers, advertise a routable hostname via the coordinator KV
        _, bound_port = self.server.address.rsplit(":", 1)
        self.address = f"{advertise_host or host}:{bound_port}"
        # lockset race witness (PS_RACE_WITNESS=1): the encode-cache
        # byte budget mutates under _enc_lock and the durable ledger
        # reference only inside _lock's apply/checkpoint critical
        # sections — the two pieces of serving/apply state a refactor
        # is most likely to touch lock-free by accident
        race_track(
            self, ("_enc_bytes", "_applied_push"),
            f"ShardServer:{self.address}",
        )

    # push-ledger bounds: wider than the reply cache's — entries are tiny
    # (short strings) and must cover a restart window, not just the last
    # in-flight call per client
    _LEDGER_SEQS = 64
    _LEDGER_CLIENTS = 1024

    def _record_push(self, cid: str, seq: str) -> None:
        """Record an applied push in the durable dedup ledger. Caller holds
        ``self._lock``: the record and the state mutation it witnesses must
        be one atomic unit with respect to ``save_state``'s snapshot."""
        per = self._applied_push.get(cid)
        if per is None:
            per = self._applied_push[cid] = OrderedDict()
            while len(self._applied_push) > self._LEDGER_CLIENTS:
                self._applied_push.popitem(last=False)
        else:
            self._applied_push.move_to_end(cid)
        per[seq] = None
        while len(per) > self._LEDGER_SEQS:
            per.popitem(last=False)

    def _bump(self, name: str) -> None:
        with self._ctr_lock:
            self.counters[name] += 1

    # -- versioned RCU state ----------------------------------------------

    @property
    def state(self) -> dict[str, Any]:
        """The published state table (RCU: immutable after publish)."""
        return self._pub[0]

    @state.setter
    def state(self, new_state: dict[str, Any]) -> None:
        """Publish a new state table AND bump the snapshot version in one
        reference swap — every writer (batched apply, serial push,
        checkpoint load) goes through here, so a pull reply's ``ver``
        always identifies exactly the table its rows came from."""
        ver = self._pub[1] + 1
        self._pub = (new_state, ver, now_wall_us())
        # flight recorder: every publish, whatever the writer — the
        # postmortem's version-regression detector reads this stream
        flightrec.record("rcu.publish", ver=ver)

    @property
    def version(self) -> int:
        """Current published snapshot version (opaque; see __init__)."""
        return self._pub[1]

    # -- serving plane: overload signal + single-flight encode cache ------

    def overloaded(self) -> bool:
        """Admission-control signal (``[serve] shed_*``): the apply queue
        is backing up or this server's withheld coalesced replies are
        pinning too many bytes — time to shed cache-backed pulls."""
        svcfg = self._serve_cfg
        if (
            svcfg.shed_queue_depth > 0
            and self._apply_q is not None
            and self._apply_q.qsize() >= svcfg.shed_queue_depth
        ):
            return True
        mb = svcfg.shed_withheld_mb
        return mb > 0 and self.server.withheld_bytes() >= (mb << 20)

    def _note_pull(self, sig: str) -> bool:
        """Count one pull of this key-set signature; True once the sig
        is HOT (its encoded reply is worth caching). The threshold keeps
        one-off training sweeps out of the encode cache."""
        c = (self._hot_counts.get(sig) or 0) + 1
        self._hot_counts.put(sig, c)
        if c == self._serve_cfg.hot_min_pulls:
            wire_counters.inc("serve_hot_keys")
        return c >= self._serve_cfg.hot_min_pulls

    def _enc_claim(self, ck: tuple) -> tuple[_EncodeEntry, bool]:
        """(entry, owner): owner=True means this pull computes the
        encode; False means another pull (possibly already finished)
        owns it and the entry's event/result are to be shared."""
        with self._enc_lock:
            ent = self._enc_cache.get(ck)
            if ent is not None:
                self._enc_cache.move_to_end(ck)
                return ent, False
            ent = self._enc_cache[ck] = _EncodeEntry()
            self._enc_evict_over_budget()
            return ent, True

    def _enc_evict_over_budget(self) -> None:
        """LRU-evict past the entry AND byte budgets (caller holds
        ``_enc_lock``). Each filled entry pins its reply payload, so the
        byte bound — not just the entry count — is what stops a server
        with multi-MB pulls pinning entries x payload of memory.
        Unfilled entries count 0; an owner filling an already-evicted
        entry notices and skips the byte accounting."""
        while self._enc_cache and (
            len(self._enc_cache) > self._enc_cap
            or self._enc_bytes > self._enc_bytes_max
        ):
            _, old = self._enc_cache.popitem(last=False)
            self._enc_bytes -= old.nbytes

    def _enc_fill(
        self, ck: tuple, ent: _EncodeEntry, rep: dict[str, Any],
        arrays: Arrays,
    ) -> None:
        """Publish the owner's finished encode to its followers and
        count its payload against the byte budget (only while the entry
        is still cached — a concurrent eviction wins)."""
        nb = sum(int(a.nbytes) for a in arrays.values())
        with self._enc_lock:
            ent.rep, ent.arrays = rep, arrays
            if self._enc_cache.get(ck) is ent:
                ent.nbytes = nb
                self._enc_bytes += nb
                self._enc_evict_over_budget()
        ent.event.set()

    def _enc_fail(self, ck: tuple, ent: _EncodeEntry) -> None:
        """The owner's encode raised: drop the entry and release any
        followers (they see ``rep is None`` and encode for themselves) —
        a poisoned entry must never park the reply lane."""
        with self._enc_lock:
            if self._enc_cache.get(ck) is ent:
                del self._enc_cache[ck]
        ent.event.set()

    def start(self) -> "ShardServer":
        self._start_apply_thread()
        self.server.start()
        return self

    def serve_forever(self) -> None:
        self._start_apply_thread()
        self.server.start()
        while not self.server._stop.wait(0.2):
            pass

    # -- batched apply engine ---------------------------------------------

    def _start_apply_thread(self) -> None:
        if self._apply_q is None or self._apply_thread is not None:
            return
        # watchdog: a non-advancing apply engine is THE server stall the
        # flight recorder exists to catch — busy means work queued or a
        # batch mid-apply; progress is the completed-batch counter.
        # The id suffix keeps the name unique per server INSTANCE: two
        # servers over the same range (tests, a restart in-process)
        # must never alias one registry entry, or one engine's exit
        # would unregister the other's probe.
        self._applying = False
        self._wd_name = (
            f"apply:{self.range.begin}-{self.range.end}:{id(self):x}"
        )

        def probe() -> tuple[bool, int]:
            q = self._apply_q
            busy = (q is not None and not q.empty()) or self._applying
            return busy, self.counters["apply_batches"]

        watchdog.register(self._wd_name, probe, thread_name="ps-apply")
        self._apply_thread = threading.Thread(
            target=self._apply_loop, daemon=True, name="ps-apply"
        )
        self._apply_thread.start()

    @staticmethod
    def _fail_stopping(item: _QueuedPush) -> None:
        """Fail a push stranded by engine shutdown with ConnectionError —
        the RPC layer severs the connection instead of sending a clean
        error reply, so the client's transport heal RESENDS the push
        (against the relaunched server, deduped by the durable ledger)
        rather than hard-failing the worker on a transient condition."""
        if not item.future.done():
            try:
                item.future.set_exception(ConnectionError(
                    "shard server stopping; push not applied"
                ))
            except Exception:  # noqa: BLE001 — the drain beat us to it
                pass

    def _enqueue_push(self, item: _QueuedPush) -> None:
        """Admit one decoded push into the apply queue (backpressure: a
        full queue parks this serving thread until the engine drains —
        which also withholds this connection's coalesced replies for the
        drain's duration, bounded by apply_queue/max_batch batch applies;
        settling deferred acks before every push instead would serialize
        the very pipeline the engine exists to batch). Never raises — a
        shutdown race resolves the item's future with ConnectionError
        instead (see _fail_stopping)."""
        q = self._apply_q
        assert q is not None
        observe_scalar("server.apply_queue.n", q.qsize() + 1)
        trace.counter("server.apply_queue_depth", q.qsize() + 1)
        while True:
            if not self._apply_open:
                self._fail_stopping(item)
                return
            try:
                q.put(item, timeout=0.05)
            except queue_mod.Full:
                continue
            if not self._apply_open:
                # raced with engine shutdown: the grace drain may already
                # have finished, leaving this item parked in a queue
                # nobody drains — fail it here (drain may also have)
                self._fail_stopping(item)
            return

    def _apply_loop(self) -> None:
        """The apply thread: drain whatever pushes have concurrently
        arrived (bounded by max_batch) and apply them as ONE coalesced
        update. Exits once the server stops, failing stragglers so no
        serving thread parks on an unresolvable deferred reply (their
        clients resend to the relaunched server; the ledger dedups)."""
        q = self._apply_q
        assert q is not None
        stop = self.server._stop
        try:
            while not stop.is_set():
                try:
                    first = q.get(timeout=0.2)
                except queue_mod.Empty:
                    continue
                batch = [first]
                limit = (
                    self._eff_batch if self._adaptive_batch
                    else self._max_batch
                )
                while len(batch) < limit:
                    try:
                        batch.append(q.get_nowait())
                    except queue_mod.Empty:
                        break
                if self._adaptive_batch:
                    self._adapt_batch(len(batch), q.qsize())
                self._applying = True
                try:
                    self._apply_batch(batch)
                except Exception:  # noqa: BLE001 — isolate the offender
                    # one malformed push (bad grad shape, poison payload)
                    # must not fail the innocent pushes it happened to
                    # coalesce with — the serial path confined the error
                    # to its own request, so does the retry: each item
                    # re-runs as its own batch and only the offender's
                    # future fails
                    for p in batch:
                        if p.future.done():
                            continue
                        try:
                            self._apply_batch([p])
                        except Exception as e1:  # noqa: BLE001
                            if not p.future.done():
                                p.future.set_exception(e1)
                finally:
                    self._applying = False
        finally:
            # the watchdog must stop probing a dead engine (and a
            # re-start() after stop re-registers a fresh probe)
            watchdog.unregister(self._wd_name)
        self._apply_open = False
        deadline = time.monotonic() + 0.5  # grace: racing enqueuers land
        while time.monotonic() < deadline:
            try:
                p = q.get_nowait()
            except queue_mod.Empty:
                time.sleep(0.05)
                continue
            self._fail_stopping(p)

    def _adapt_batch(self, got: int, backlog: int) -> None:
        """Adaptive batch-ceiling policy (``[server] adaptive_batch``),
        called by the apply thread after each drain with the batch it
        actually collected and the queue depth left behind. A FULL batch
        with more still queued means arrivals outpace the ceiling —
        double it (the drain is leaving coalescing wins on the table); a
        batch far below the ceiling means arrivals are sparse — halve it,
        so one slow client's trickle is applied at low latency instead of
        waiting to fill a ceiling sized for a burst. Every change bumps
        ``server_batch_adapts``; ``max_batch`` stays the hard ceiling."""
        eff = self._eff_batch
        if got >= eff and backlog > 0 and eff < self._max_batch:
            self._eff_batch = min(eff * 2, self._max_batch)
        elif got <= max(1, eff // 4) and eff > 1:
            self._eff_batch = max(1, eff // 2)
        if self._eff_batch != eff:
            wire_counters.inc("server_batch_adapts")

    def _apply_batch(self, batch: list[_QueuedPush]) -> None:
        """Coalesce and apply one batch: segment-sum duplicate keys across
        the batch's pushes, ONE updater delta over the union of touched
        rows, the whole batch recorded in the durable ledger atomically
        with the state publish (save_state can never snapshot a state
        that disagrees with its ledger)."""
        flightrec.record("apply.begin", pushes=len(batch))
        todo: list[_QueuedPush] = []
        dups: list[_QueuedPush] = []
        commit_ver = 0
        t_apply0 = t_apply1 = 0.0
        with self._lock:
            seen: set[tuple[str | None, str | None]] = set()
            for p in batch:
                if p.cid is not None:
                    per = self._applied_push.get(p.cid)
                    if per is not None and p.seq in per:
                        # already applied (and ledgered) in a previous
                        # server life: durably done — ack immediately
                        self._bump("push_replays")
                        wire_counters.inc("rpc_dedup_hits")
                        flightrec.record(
                            "apply.replay", cid=p.cid, seq=p.seq,
                        )
                        if not p.future.done():
                            p.future.set_result(({"ok": True}, {}))
                        continue
                    if (p.cid, p.seq) in seen:
                        # duplicate within THIS batch: its first instance
                        # has not applied yet, so the ack must WAIT for
                        # the publish — acking now would break 'acked =>
                        # durably applied' if the apply then fails
                        self._bump("push_replays")
                        wire_counters.inc("rpc_dedup_hits")
                        dups.append(p)
                        continue
                    seen.add((p.cid, p.seq))
                todo.append(p)
            if todo:
                t_apply0 = time.perf_counter()
                # pad_to_pow2: a coalesced union has a different length
                # every batch, and each fresh shape re-dispatches the
                # whole eager updater chain — the pow-2 bucket pins
                # batches to a handful of compiled shapes (pad rows are
                # PAD_KEY 0 + zero grad, which every updater maps to a
                # zero delta per the store invariant)
                # psl: ignore[blocking-under-lock]: the apply lock must span the ledger check, the coalesce+jitted apply and the publish — the serial raw-frame path mutates state under this same lock, so an unlocked compute window would lose any raw push that interleaved
                idx, grad = kv_store.coalesce_pushes(
                    [p.keys for p in todo], [p.grad for p in todo],
                    pad_to_pow2=True,
                )
                with trace.span(
                    "server.apply_batch", cat="ps",
                    pushes=len(todo), keys=len(idx),
                ):
                    # ONE jitted dispatch for the whole batch (the
                    # bucketed shapes keep the compile count at ~one per
                    # pow-2 union size). Deliberately NOT donated: the
                    # old buffers must stay valid for concurrent RCU
                    # snapshot readers (pull/dump) until they drop them.
                    new_state = kv_store.push(
                        self.updater, self.state,
                        self._jnp.asarray(idx), self._jnp.asarray(grad),  # psl: ignore[blocking-under-lock]: same unit as the coalesce above — ledger check, jitted apply and RCU publish are one atomic section vs the serial raw-frame path
                    )
                    for p in todo:
                        if p.cid is not None:
                            self._record_push(p.cid, p.seq)
                    # RCU publish: ONE reference swap — pull/dump capture
                    # self.state without the lock and see the pre- or
                    # post-batch table, never a torn mix
                    self.state = new_state
                    commit_ver = self.version
        t_apply1 = time.perf_counter()
        #: jitted-apply duration for this batch (the latency-forensics
        #: apply segment, echoed on replies and the updater markers)
        apl_us = int(max(t_apply1 - t_apply0, 0.0) * 1e6) if todo else 0
        if todo:
            # the postmortem's AND the live auditor's acked-vs-applied
            # ledger: every (cid, seq) this commit made durable, against
            # the version it produced. The full batch, never a slice —
            # a truncated ledger makes the streaming ack⇒applied monitor
            # read the tail pushes as acked-but-unapplied on a healthy
            # cluster whenever [server] max_batch exceeds the cap (the
            # event stays bounded by max_batch, an operator knob)
            flightrec.record(
                "apply.commit", ver=commit_ver, pushes=len(todo),
                pairs=[
                    [p.cid, p.seq] for p in todo if p.cid is not None
                ],
            )
        if todo:
            # per-range matrix: applied pushes, their payload bytes and
            # the jitted-apply cost (the batch's, once — the coalesced
            # apply IS this range's cost, not per-push)
            self._range_scope.push(
                len(todo), sum(int(p.grad.nbytes) for p in todo)
            )
            self._range_scope.apply(max(t_apply1 - t_apply0, 0.0))
        with self._ctr_lock:
            self.counters["pushes"] += len(todo)
            self.counters["apply_batches"] += 1
            # only genuinely APPLIED pushes count as coalesced — counting
            # ledger replays/duplicates would inflate the batching win by
            # exactly the dedup traffic
            self.counters["push_coalesced"] += max(len(todo) - 1, 0)
        if len(todo) > 1:
            wire_counters.inc("push_coalesced", len(todo) - 1)
        observe_scalar("server.apply_batch.n", len(batch))
        trace.counter("server.apply_batch_size", len(batch))
        if trace.enabled():
            # per-push updater spans re-join each caller's trace across
            # the thread hop (the PR-2 contract: one logical push is one
            # trace id, client span -> dispatch span -> updater span).
            # The marker fires AFTER the batch applied, so it carries
            # the measured queue-wait/apply split as args — the
            # critical-path engine reads them to split the post-dispatch
            # gap into apply_wait vs apply (jit compiles land in the
            # right column)
            for p in todo:
                with trace.activate(p.tctx), trace.span(
                    "server.updater", cat="ps",
                    keys=len(p.keys), batched=len(todo),
                    apw_us=int(max(t_apply0 - p.t_enq, 0.0) * 1e6),
                    apl_us=apl_us,
                ):
                    pass
        # dups resolve here too: the publish they waited on has happened
        # (on an exception above, neither list resolves — the apply loop's
        # per-item retry re-runs them, and a dup then replays off the
        # ledger its first instance just wrote). The reply carries the
        # apply-segment timings (_apw_us queue wait, _apl_us jitted
        # apply) the RPC layer's _svc_us echo can't see from outside —
        # the latency-forensics split of "server" into its real phases.
        for p in todo + dups:
            if not p.future.done():  # the shutdown race may fail one first
                try:
                    p.future.set_result((
                        {
                            "ok": True,
                            "_apw_us": int(
                                max(t_apply0 - p.t_enq, 0.0) * 1e6
                            ),
                            "_apl_us": apl_us,
                        },
                        {},
                    ))
                except Exception:  # noqa: BLE001 — lost the race benignly
                    pass

    # -- checkpoint/restart (ref: each server dumps its own key range;
    # resume = reload the range before continuing) ------------------------

    def _ckpt_path(self, ckpt_dir: str) -> str:
        import os

        r = self.range
        return os.path.join(ckpt_dir, f"server-{r.begin}-{r.end}.npz")

    def save_state(self, ckpt_dir: str) -> None:
        """Atomic dump of this range's updater state (tmp + rename: a
        crash mid-write never leaves a torn checkpoint at the final path;
        writers serialize so the final shutdown dump can't interleave with
        an in-flight periodic dump on the shared tmp file)."""
        import os

        with trace.span(
            "server.checkpoint.save", cat="ckpt",
            range=f"{self.range.begin}-{self.range.end}",
        ):
            with self._lock:
                # same critical section for the state REFERENCE and the
                # ledger: the ledger in a checkpoint must witness exactly
                # the pushes that checkpoint contains — never one more,
                # never one fewer. Only the reference capture needs the
                # lock (the published dict is immutable after the RCU
                # swap); the device->host transfer below runs OUTSIDE it
                # (pslint blocking-under-lock true positive: the full-
                # state D2H sync used to stall every push for the
                # checkpoint's duration).
                state = self.state
                ledger = json.dumps(
                    {cid: list(per) for cid, per in self._applied_push.items()}
                )
            host = {k: np.asarray(v) for k, v in state.items()}
            with self._ckpt_write_lock:
                os.makedirs(ckpt_dir, exist_ok=True)
                path = self._ckpt_path(ckpt_dir)
                tmp = path + ".tmp.npz"  # .npz: savez must not append one
                np.savez(
                    tmp,
                    __push_ledger__=np.frombuffer(
                        ledger.encode(), dtype=np.uint8
                    ),
                    **host,
                )
                os.replace(tmp, path)

    def load_state(self, ckpt_dir: str) -> bool:
        """Load this range's dump if one exists; False when absent."""
        import os

        path = self._ckpt_path(ckpt_dir)
        if not os.path.exists(path):
            return False
        with trace.span("server.checkpoint.load", cat="ckpt"), np.load(
            path
        ) as z:
            host = {k: z[k] for k in z.files}
        ledger_raw = host.pop("__push_ledger__", None)
        if set(host) != set(self.state) or any(
            host[k].shape != tuple(self.state[k].shape) for k in host
        ):
            raise ValueError(
                f"checkpoint {path} does not match this server's state "
                "layout (different updater or key range?)"
            )
        applied: OrderedDict[str, OrderedDict[str, None]] = OrderedDict()
        if ledger_raw is not None:  # absent in pre-ledger checkpoints
            for cid, seqs in json.loads(ledger_raw.tobytes().decode()).items():
                applied[cid] = OrderedDict((str(s), None) for s in seqs)
        # host->device transfer OUTSIDE the lock (pslint
        # blocking-under-lock): only the two reference swaps need the
        # critical section — they form the same atomic state+ledger unit
        # save_state snapshots
        new_state = {k: self._jnp.asarray(v) for k, v in host.items()}
        with self._lock:
            self.state = new_state
            self._applied_push = applied
        return True

    def start_checkpointing(self, ckpt_dir: str, interval_s: float) -> None:
        """Background periodic dumps until the server stops (pushes since
        the last dump are lost on a crash — the bounded-staleness price the
        reference's recovery design also pays)."""

        def loop() -> None:
            while not self.server._stop.wait(interval_s):
                self.save_state(ckpt_dir)

        self._ckpt_thread = threading.Thread(target=loop, daemon=True)
        self._ckpt_thread.start()

    def stop_checkpointing(self) -> None:
        """Join the periodic dump thread (the stop event must already be
        set — serve_forever has returned)."""
        if self._ckpt_thread is not None:
            self._ckpt_thread.join(timeout=30)
            self._ckpt_thread = None

    def _resolve_keys(
        self, h: dict[str, Any], arrays: Arrays
    ) -> np.ndarray | None:
        """Key-caching filter, server side: prefer the cached list for this
        (worker, signature); fall back to the sent keys and cache them."""
        ck = (int(h["worker"]), h["sig"])
        if "keys" in arrays:
            keys = arrays["keys"].astype(np.int64)
            self._key_cache.put(ck, keys)
            return keys
        keys = self._key_cache.get(ck)
        if keys is None:
            self._bump("need_keys")
            return None
        self._bump("cache_hits")
        return keys

    def _handle(self, h: dict[str, Any], arrays: Arrays):
        cmd = h["cmd"]
        if cmd == "pull":
            return self._handle_pull(h, arrays)
        if cmd == "push":
            cid = h.get("_cid")
            seq = None if cid is None else str(h.get("_seq"))
            if cid is not None:
                with self._lock:
                    per = self._applied_push.get(cid)
                    if per is not None and seq in per:
                        # this exact push already mutated state in a
                        # previous server life; its reply died with the
                        # kill, and the resend must not re-apply
                        self._bump("push_replays")
                        wire_counters.inc("rpc_dedup_hits")
                        flightrec.record("apply.replay", cid=cid, seq=seq)
                        return {"ok": True}, {}
            keys = self._resolve_keys(h, arrays)
            if keys is None:
                # _transient: nothing committed — the reply cache must NOT
                # pin this bounce, so the keyed follow-up (same seq) re-runs
                return {"ok": True, "need_keys": True, "_transient": True}, {}
            g = self._decode_grad(h, arrays).reshape(len(keys), -1)
            # per-key heat (ISSUE 9): pushed GLOBAL keys feed the
            # count-min the replication/tier-promotion planes will read
            key_heat.add(np.asarray(keys, np.int64) + self.range.begin)
            if (
                self._apply_q is not None
                and self._apply_thread is not None
                and cid is not None
            ):
                # engine path only once start() armed the apply thread: a
                # handler driven directly (tests, tools) keeps the inline
                # path instead of deferring onto a thread nobody runs
                # batched apply engine: enqueue the DECODED push and defer
                # the reply — the serving thread keeps draining buffered
                # requests (pulls flow past queued pushes) and the RPC
                # layer settles this reply once the batch applied, so an
                # acked push is still a durably recorded one. Raw no-cid
                # frames keep the inline path: their reply ordering
                # contract has no seq echo to survive deferral.
                item = _QueuedPush(
                    np.asarray(keys), np.asarray(g), cid, seq,
                    # the dispatch span's identity: the apply thread's
                    # server.updater span re-joins this push's trace
                    tctx=trace.wire_context() if trace.enabled() else None,
                )
                self._enqueue_push(item)
                return DeferredReply(item.future), {}
            # serial path ([server] apply_queue = 0): apply inline under
            # the write lock — the pre-engine discipline, kept as the
            # bench baseline and the raw-frame fallback
            with trace.span("server.updater", cat="ps", keys=len(keys)):
                with self._lock:
                    rows = {k: v[keys] for k, v in self.state.items()}
                    # psl: ignore[blocking-under-lock]: the serial path ([server] apply_queue = 0) applies INLINE under the write lock by definition — that serialization is the pre-engine baseline discipline the engine is benchmarked against
                    deltas = self.updater.delta(rows, self._jnp.asarray(g))
                    self.state = {
                        k: self.state[k].at[keys].add(deltas[k])
                        for k in self.state
                    }
                    if cid is not None:
                        self._record_push(cid, seq)
                serial_ver = self.version
            self._bump("pushes")
            self._range_scope.push(1, int(np.asarray(g).nbytes))
            flightrec.record(
                "apply.commit", ver=serial_ver, pushes=1,
                pairs=[[cid, seq]] if cid is not None else [],
            )
            return {"ok": True}, {}
        if cmd == "dump":
            state = self.state  # RCU snapshot (see pull)
            w = np.asarray(self.updater.weights(state))
            return {"ok": True, "begin": self.range.begin, "end": self.range.end}, {
                "w": w
            }
        if cmd == "stats":
            rep = {
                "ok": True,
                **self.counters,
                # current RCU publish version. NOT the key "ver": that
                # is a binary-header-v2 slot, and stats replies must
                # stay v1-decodable to old binary peers
                "state_ver": self.version,
                "bytes_out": self.server.bytes_out,
                "bytes_in": self.server.bytes_in,
                "frames_in": self.server.frames_in,
                "cached_sigs": len(self._key_cache),
                # recovery observability: resent/duplicated frames this
                # server answered from the reply cache instead of
                # re-applying (process-wide counter; one server per
                # process in the spawned tier)
                "rpc_dedup_hits": wire_counters.get("rpc_dedup_hits"),
                # serving observability: quantized-pull payload savings
                # (process-wide, like rpc_dedup_hits above)
                "wire_quant_bytes_saved": wire_counters.get(
                    "wire_quant_bytes_saved"
                ),
            }
            faults = self.server.fault_stats()
            if faults is not None:
                rep["faults"] = faults
            return rep, {}
        if cmd == "shutdown":
            raise RpcServer.Shutdown
        raise ValueError(f"unknown server command {cmd!r}")

    def _handle_pull(
        self, h: dict[str, Any], arrays: Arrays
    ) -> tuple[dict[str, Any], Arrays]:
        """The read path (ISSUE 7 serving plane). In order:

        1. conditional pull: ``if_newer=<ver>`` against an unchanged
           snapshot answers ``not_modified`` — no gather, no encode, no
           payload (the client re-arms its TTL on its cached rows);
        2. admission control: under overload, a revalidation the client
           flagged ``shed_ok`` (it holds a within-bounds cached
           fallback) is shed with a retry-after hint instead of
           queueing an encode behind the backlog;
        3. single-flight encode: concurrent/repeated pulls of a HOT key
           set against the same snapshot share ONE encoded reply — the
           buffers are reused across the reply lane, not re-gathered
           per client.

        Replies to VERSION-AWARE pulls (``sv: 1``, sent by serving
        handles; implied by ``if_newer``) carry ``ver``, the RCU publish
        version of exactly the table the rows came from. Pulls without
        the signal get the PR-6 reply shape byte for byte: ``ver`` is a
        binary-header-v2 slot, and stamping it into every reply would
        livelock a v1-binary peer in a mixed cluster (the ``sv`` signal
        itself rides the request's JSON tail, so first-contact requests
        stay v1-decodable everywhere)."""
        keys = self._resolve_keys(h, arrays)
        if keys is None:
            return {"ok": True, "need_keys": True}, {}
        # RCU snapshot read: ONE reference capture of the published
        # (state, version) pair (the apply thread swaps a complete new
        # tuple per batch, never mutates one in place), so this pull
        # sees the pre- or post-batch table — never a torn mix, never a
        # version that disagrees with its rows — without the write lock
        state, ver, pts = self._pub  # psl: ignore[rcu]: THE sanctioned lock-free read — one atomic capture of the whole (state, version, publish-ts) tuple; the state/version properties would be two captures and could pair rows with a foreign version
        ifn = h.get("if_newer")
        sv = bool(h.get("sv")) or ifn is not None
        if ifn is not None and int(ifn) == ver:
            # the client's cached rows ARE this snapshot (equality, not
            # ordering: versions are opaque per-life snapshot ids)
            self._bump("pulls")
            self._bump("not_modified")
            wire_counters.inc("serve_not_modified")
            self._range_scope.pull(0)
            # pts: the publish ts of the snapshot the client's cached
            # rows ARE — the wire layer turns it into a per-serve
            # ``_age_us`` (see control.decorated), and the client
            # re-anchors its cache entry's age off this revalidation
            return {
                "ok": True, "not_modified": True, "ver": ver, "pts": pts,
            }, {}
        if ifn is not None and h.get("shed_ok") and self.overloaded():
            # shed: the client promised a cached fallback within its
            # staleness ceiling — tell it to keep serving that and come
            # back, instead of queueing rows behind a saturated engine.
            # No ``ver``: nothing was validated, so the client must not
            # re-arm version trust off this reply.
            self._bump("pulls")
            self._bump("shed")
            wire_counters.inc("serve_shed")
            flightrec.record("serve.shed", sig=h.get("sig"))
            return {"ok": True, "not_modified": True, "shed": True,
                    "retry_after_ms": self._serve_cfg.retry_after_ms}, {}
        qn = int(h.get("quant", 0))
        ent = None
        hot = self._enc_cap > 0 and self._note_pull(h["sig"])
        # sv is part of the cache key: a version-stamped reply cached
        # for a serving client must never be replayed to a client that
        # can't decode the v2 header slot (and vice versa)
        ck = (
            h["sig"], ver, qn, int(h.get("qseg", 256)),
            bool(h.get("zip")), sv,
        )
        if hot:
            ent, owner = self._enc_claim(ck)
            if not owner:
                # single-flight: another pull of the same keys against
                # the same snapshot owns the encode — share its buffers
                # (the wait parks only on a concurrent first encode; a
                # finished entry's event is already set)
                if ent.event.wait(timeout=5.0) and ent.rep is not None:
                    self._bump("pulls")
                    self._bump("encode_reuse")
                    wire_counters.inc("serve_encode_reuse")
                    self._range_scope.pull(
                        sum(a.nbytes for a in ent.arrays.values())
                    )
                    self._range_scope.age(skew_clamped_age_s(pts))
                    return ent.rep, ent.arrays
                ent = None  # owner failed or timed out: encode ourselves
        try:
            # snapshot materialization is gated on hot AND a conditional
            # pull (`if_newer` proves a caching serving client): a
            # training tier with epoch-repeated key sets and per-step
            # version churn must never pay a full-table weights()
            # materialization per step just because its sigs went hot
            rep, out = self._encode_pull(
                state, ver, keys, h, qn, hot and ifn is not None,
                with_ver=sv, pts=pts,
            )
        except BaseException:
            if ent is not None:
                self._enc_fail(ck, ent)
            raise
        self._bump("pulls")
        self._bump("pull_encodes")
        # per-range matrix: rows left this range at this snapshot's age
        # (publish and serve clocks are usually this process's own, but
        # a replicated pts can be a peer's — the clamp absorbs the skew)
        self._range_scope.pull(sum(a.nbytes for a in out.values()))
        self._range_scope.age(skew_clamped_age_s(pts))
        if ent is not None:
            self._enc_fill(ck, ent, rep, out)
        return rep, out

    def _host_weights(self, state: dict[str, Any], ver: int) -> np.ndarray:
        """Full weights table for snapshot ``ver``, materialized on the
        host ONCE per version that receives a hot pull and shared by
        every encode at that version: a hot pull becomes a numpy
        fancy-index (~us) instead of an eager jax gather + weights
        dispatch per request (~ms). Bounded by ``[serve]
        snapshot_keys_max`` — the caller gates on the range size, so a
        10^9-key training shard never pays a full-table device->host
        sync for one read. Benign race: two threads materializing a
        fresh version duplicate the work; the tuple swap is atomic and
        last-writer-wins, never torn."""
        cur = self._host_w
        if cur is not None and cur[0] == ver:
            return cur[1]
        w = np.asarray(self.updater.weights(state)).reshape(
            self.range.size, -1
        )
        self._host_w = (ver, w)
        return w

    def _encode_pull(
        self, state: dict[str, Any], ver: int, keys: np.ndarray,
        h: dict[str, Any], qn: int, snap: bool = False,
        with_ver: bool = False, pts: int = 0,
    ) -> tuple[dict[str, Any], Arrays]:
        """Gather + encode one pull reply from an RCU snapshot (shared
        verbatim across clients by the single-flight cache — nothing
        here may depend on the requesting connection). ``snap`` allows
        MATERIALIZING the per-version host weights snapshot (hot +
        revalidation traffic, ranges within ``snapshot_keys_max``); an
        already-current snapshot serves every pull either way, and
        everything else keeps the per-row jax path."""
        # per-key heat, read side: only REAL row encodes count (a
        # not_modified / shed / single-flight-reused reply moves no
        # rows, so it adds no promotion-relevant heat — and the serving
        # fast paths stay sketch-free)
        key_heat.add(np.asarray(keys, np.int64) + self.range.begin)
        cur = self._host_w
        if cur is not None and cur[0] == ver:
            # a snapshot for THIS version is already materialized (some
            # hot pull paid for it): every pull may ride it for free
            w = cur[1][keys]
        elif snap and 0 < self.range.size <= self._serve_cfg.snapshot_keys_max:
            w = self._host_weights(state, ver)[keys]
        else:
            rows = {k: v[keys] for k, v in state.items()}
            w = np.asarray(self.updater.weights(rows)).reshape(len(keys), -1)
        if qn:
            # quantized pull (read-mostly/serving traffic): the rows
            # ride as per-segment-scale integers at the width the
            # client asked for. Only quant-negotiated clients send
            # the field, so an old client can never receive a
            # payload it can't decode. Round-to-NEAREST, not
            # stochastic: reads have no error-feedback loop, so
            # nearest halves the worst-case error and keeps repeated
            # reads of one unchanged snapshot bit-identical.
            from parameter_server_tpu.filters.quant import SegmentQuantizer

            qz = SegmentQuantizer(qn, int(h.get("qseg", 256)))
            q, qs = qz.encode_nearest(w.ravel())
            wire_counters.inc(
                "wire_quant_bytes_saved",
                max(w.nbytes - q.nbytes - qs.nbytes, 0),
            )
            rep = {"ok": True, "codec": qn, "qseg": qz.seg}
            if with_ver:  # see _handle_pull: only version-aware clients
                rep["ver"] = ver
                if pts:
                    rep["pts"] = pts  # freshness: version-constant, so
                    # safe on single-flight-shared replies; the wire
                    # layer derives each serve's _age_us from it
            return rep, {"q": q, "qs": qs}
        rep = {"ok": True, "zip": h.get("zip", False)}
        if with_ver:
            rep["ver"] = ver
            if pts:
                rep["pts"] = pts
        return rep, {"w": w.ravel()}

    def _decode_grad(self, h: dict[str, Any], arrays: Arrays) -> np.ndarray:
        codec_bytes = int(h.get("codec", 0))
        if not codec_bytes:
            return arrays["g"]
        if "qs" in arrays:
            # per-segment-scale codec (filters/quant.py, the negotiated
            # "qwire" path): dequantize here on the serving thread — the
            # decoded float grad then enters the apply queue, where
            # coalesce_pushes segment-sums it into the engine's single
            # jitted dispatch like any other push
            from parameter_server_tpu.filters.quant import SegmentQuantizer

            qz = SegmentQuantizer(codec_bytes, int(h.get("qseg", 256)))
            return qz.decode(arrays["q"], arrays["qs"])
        # legacy whole-array affine codec (filters/fixed_point, the
        # un-negotiated [filter] fixing_float_bytes knob)
        from parameter_server_tpu.filters.fixed_point import Encoded, FixedPointCodec

        codec = FixedPointCodec(num_bytes=codec_bytes)
        e = Encoded(
            self._jnp.asarray(arrays["q"]),
            self._jnp.asarray(arrays["lo"][0]),
            self._jnp.asarray(arrays["scale"][0]),
        )
        return np.asarray(codec.decode(e))


class ServerHandle:
    """Worker-side proxy to one shard server, applying the send filters
    (ref: SharedParameter's per-call FilterConfigs)."""

    def __init__(
        self,
        address: str,
        rank: int,
        worker: int,
        cfg: PSConfig,
        range_size: int = 0,
        resolve_addr=None,  # () -> current address, for server-restart recovery
        reconnect_timeout_s: float | None = None,
        serving: bool = False,
        key_cache=None,
        key_range: KeyRange | None = None,
    ):
        """``serving=True`` marks this handle as part of the read-mostly
        serving tier: with ``[serve] cache`` on, it arms the client-side
        versioned key cache (filters/keycache.py) — pulls are served
        locally within the TTL, revalidated by version past it, and
        invalidated exactly by this handle's own pushes. ``key_cache``
        lets a serving FRONTEND share ONE cache across ALL its handles —
        same shard or a whole multi-shard cluster (many connections, one
        process-wide working set): entries and the inverted invalidation
        index are namespaced by this handle's ``rank``, so two shards'
        range-relative keys can never collide or cross-invalidate, and
        invalidation stays exact because every handle's pushes
        invalidate the shared instance under its own rank. The
        training tier NEVER passes serving=True: a trainer's staleness
        contract is the SSP clock, not a TTL (see ``_connect_servers``).

        ``key_range`` (optional) names the server range this handle
        proxies: with it, every serve this CLIENT answers — cached,
        bounded-stale, shed-fallback or fresh off the wire — books its
        realized data age into that range's matrix alongside the
        server's own bookings (freshness plane, ISSUE 17)."""
        import itertools

        self.rank = rank
        self.worker = worker
        self._range_scope = (
            RangeScope(key_range.begin, key_range.end)
            if key_range is not None else None
        )
        self._kcache = None
        if serving and cfg.serve.cache:
            from parameter_server_tpu.filters.keycache import ClientKeyCache

            # `is not None`, NOT `or`: the cache defines __len__, so a
            # shared instance that happens to be empty is falsy — `or`
            # would silently hand every handle a private cache
            self._kcache = key_cache if key_cache is not None else (
                ClientKeyCache(
                    cap=cfg.serve.cache_entries,
                    ttl_s=cfg.serve.ttl_ms / 1e3,
                    max_stale_s=cfg.serve.max_stale_ms / 1e3,
                )
            )
        self._resolve_addr = resolve_addr
        self._reconnect_timeout_s = (
            reconnect_timeout_s
            if reconnect_timeout_s is not None
            else cfg.fault.reconnect_timeout_s
        )
        # client-internal same-address retry window: short, so transient
        # connection loss (injected faults, restarts on the same port)
        # heals in-place with the SAME sequence numbers (dedup-safe), while
        # a genuinely moved server falls through to the resolver loop in
        # _keyed_call quickly instead of burning the whole handle window
        self._client_window_s = min(3.0, self._reconnect_timeout_s)
        self._pipeline_window = max(1, cfg.wire.window)
        self._hdr_codec = cfg.wire.hdr_codec
        self._adaptive_window = cfg.wire.adaptive_window
        # quantized push transport ([wire] quant, filters/quant.py):
        # negotiated per connection via the "qwire" feature advert —
        # until (unless) the peer acks, pushes stay on the float path
        qmode = cfg.wire.quant
        if qmode not in ("off", "int8", "int16"):
            raise ValueError(
                f"[wire] quant must be off|int8|int16, got {qmode!r}"
            )
        self._quant_bytes = {"off": 0, "int8": 1, "int16": 2}[qmode]
        self._quant_pull = bool(cfg.wire.quant_pull) and self._quant_bytes > 0
        self._features = (
            frozenset({"qwire"}) if self._quant_bytes else frozenset()
        )
        if self._quant_bytes:
            from parameter_server_tpu.filters.quant import SegmentQuantizer

            self._quantizer = SegmentQuantizer(
                self._quant_bytes, max(1, int(cfg.wire.quant_seg))
            )
        # error-feedback accumulator: the residual each quantized push
        # loses to rounding, folded into the NEXT push of the same keys.
        # Folded exactly once per logical push at encode time (resends
        # reuse the encoded payload), guarded by its own lock so a
        # recovery-thread re-encode can never race the worker loop.
        self._res_lock = threading.Lock()
        self._residual: np.ndarray | None = None
        self._res_vdim = 0
        self._res_range = int(range_size)
        self._res_map: dict[int, int] | None = None
        self.client = RpcClient(
            address, reconnect_timeout_s=self._client_window_s,
            window=self._pipeline_window,
            hdr_codec=self._hdr_codec,
            adaptive_window=self._adaptive_window,
            features=self._features,
        )
        # a worker's pull and in-flight push threads share this handle;
        # concurrent failures must rebuild the connection once — the
        # generation counter lets a late-arriving failing thread see that
        # another thread already replaced the client and just retry
        self._reconnect_lock = threading.Lock()
        # the recovery-executor singleton gets its OWN lock (pslint
        # blocking-under-lock true positive): _recovery() used to share
        # _reconnect_lock, so the client's READER thread — which calls
        # _recovery() from a completion callback — could park for a full
        # reconnect window behind a thread sleeping inside _reconnect,
        # stalling every other in-flight completion on that connection
        self._pool_lock = threading.Lock()
        self._conn_gen = 0
        self._sent_sigs = _LruSigs()
        self._key_caching = cfg.filter.key_caching
        self._zip = cfg.filter.compressing
        self._codec_bytes = cfg.filter.fixing_float_bytes
        # local (range-relative) keys ride the wire as u32 when the range
        # fits, u64 otherwise — a silent u32 truncation at 10^9+ feature
        # scale would corrupt the model
        self._key_dtype = (
            np.uint64 if range_size > (1 << 32) else np.uint32
        )
        # atomic: concurrent in-flight push threads must not reuse a
        # stochastic-rounding seed
        self._quant_seed = itertools.count()
        # logical-call sequence numbers ("k<n>" — a namespace disjoint from
        # RpcClient's internal integer counter): one per _keyed_call, held
        # constant across client rebuilds so every delivery of a logical
        # push is one dedup identity on the server
        self._kseq = itertools.count()
        # lazy single-thread executor for the RESOLVER retry path of async
        # calls: a reader thread completing a failed future must never run
        # the blocking reconnect loop itself
        self._recovery_pool: ThreadPoolExecutor | None = None
        # watchdog: this handle's client carries only pull/push/dump/stats
        # (nothing that legitimately parks), so in-flight requests whose
        # completions stop moving mean a reader parked past every
        # deadline — one of the stalls the flight recorder dumps on.
        # ``self.client`` is re-read per poll, so the probe follows
        # recovery rebuilds.
        self._wd_name = f"handle:{rank}:w{worker}:{id(self):x}"
        watchdog.register(
            self._wd_name, lambda: self.client.stall_probe(),
            thread_name="ps-rpc-reader",
        )
        if self._codec_bytes:
            from parameter_server_tpu.filters.fixed_point import FixedPointCodec

            self._codec = FixedPointCodec(num_bytes=self._codec_bytes)
        # lockset race witness (PS_RACE_WITNESS=1): the error-feedback
        # residual state is shared between the worker loop and the
        # recovery/reader threads — every access must hold _res_lock or
        # the exactly-once folding guarantee is a race away from double
        # counting
        race_track(
            self, ("_residual", "_res_map", "_res_vdim"),
            f"ServerHandle:{rank}:w{worker}",
        )

    def _keyed_call(
        self, cmd: str, keys: np.ndarray, arrays: Arrays,
        lseq: str | None = None, **fields,
    ):
        """Issue a keyed request, sending the key list only when the server
        doesn't hold it (key-caching filter, worker side). A lost
        connection triggers reconnect-and-retry against the (possibly
        relaunched) server when a resolver was provided. ``lseq`` re-enters
        a logical call that already holds a dedup identity (the async
        recovery path); fresh calls allocate their own."""
        if lseq is None:
            lseq = f"k{next(self._kseq)}"
        gen = self._conn_gen
        try:
            return self._keyed_call_once(cmd, keys, arrays, lseq, **fields)
        except (ConnectionError, BrokenPipeError, OSError):
            if self._resolve_addr is None:
                raise
        # retry until the reconnect window closes: one retry is not enough
        # around a server death — a connect can land in the dying listen
        # socket's backlog (or reach a not-yet-serving replacement) and
        # then reset on first use
        t0 = time.monotonic()
        deadline = t0 + self._reconnect_timeout_s
        while True:
            self._reconnect(gen, deadline)
            gen = self._conn_gen
            try:
                return self._keyed_call_once(cmd, keys, arrays, lseq, **fields)
            except (ConnectionError, BrokenPipeError, OSError) as e:
                if time.monotonic() > deadline:
                    raise ConnectionError(
                        f"server rank {self.rank} kept resetting for "
                        f"{time.monotonic() - t0:.1f}s across reconnects: {e}"
                    ) from e
                # backoff: a connect that succeeds into a dying backlog and
                # resets on first use would otherwise hot-loop at full speed
                time.sleep(0.3)

    def _reconnect(self, failed_gen: int, deadline: float | None = None) -> None:
        """Rebuild the connection to wherever this rank's server now lives
        (ref: re-resolving the node registry after recovery). The relaunch
        starts with an empty key cache, so our sent-signature memory is
        dropped; the need_keys protocol would also recover, at one extra
        round-trip per cached set.

        failed_gen: the connection generation the caller's failure was
        observed on — if another thread already replaced that connection,
        this call must NOT tear the fresh one down, just retry on it.
        deadline: caller's overall monotonic deadline (the retry loop's);
        defaults to a fresh reconnect window."""
        if deadline is None:
            deadline = time.monotonic() + self._reconnect_timeout_s
        with self._reconnect_lock:
            if self._conn_gen != failed_gen:
                return  # a concurrent failure already rebuilt the client
            self.client.close()
            # the rebuilt client must BE the old one to the server's dedup
            # machinery: same cid so retried "k<n>" seqs are recognized,
            # start_seq past the old internal counter so fresh un-keyed
            # calls (dump/stats) can't collide with cached old replies
            cid, next_seq = self.client.identity
            last: Exception | None = None
            while time.monotonic() < deadline:
                try:
                    addr = self._resolve_addr()
                    # psl: ignore[blocking-under-lock]: _reconnect_lock IS the serialization of connection rebuilds — concurrent failing threads must park until exactly one rebuild completes; no completion/reader thread takes it (the recovery pool moved to _pool_lock)
                    self.client = RpcClient(
                        addr, retries=1,
                        reconnect_timeout_s=self._client_window_s,
                        cid=cid, start_seq=next_seq,
                        window=self._pipeline_window,
                        hdr_codec=self._hdr_codec,
                        adaptive_window=self._adaptive_window,
                        # feature negotiation restarts with the rebuilt
                        # connection: a downgraded replacement server
                        # simply never acks, and pushes drop to floats
                        features=self._features,
                    )
                    self._sent_sigs = _LruSigs()
                    self._conn_gen += 1
                    return
                except (ConnectionError, OSError) as e:
                    last = e
                    # psl: ignore[blocking-under-lock]: rebuild-retry backoff under the rebuild serialization lock — waiters WANT to park until the one rebuild lands (see the pragma above)
                    time.sleep(0.3)
        raise ConnectionError(
            f"server rank {self.rank} unreachable for "
            f"{self._reconnect_timeout_s}s: {last}"
        )

    def _keyed_call_once(
        self, cmd: str, keys: np.ndarray, arrays: Arrays, lseq: str, **fields
    ):
        sig = _sig(keys)
        send_keys = not (self._key_caching and sig in self._sent_sigs)
        payload = dict(arrays)
        if send_keys:
            payload["keys"] = keys.astype(self._key_dtype)
        rep, out = self.client.call(
            cmd, arrays=payload, worker=self.worker, sig=sig,
            zip=self._zip, _seq=lseq, **fields,
        )
        if rep.get("need_keys"):  # cache miss on a sig we believed was cached
            # SAME lseq: a need_keys bounce is marked non-committing server
            # side, so this follow-up re-runs the handler while the logical
            # mutation keeps a single dedup identity end to end
            payload["keys"] = keys.astype(self._key_dtype)
            rep, out = self.client.call(
                cmd, arrays=payload, worker=self.worker, sig=sig,
                zip=self._zip, _seq=lseq, **fields,
            )
        self._sent_sigs.put(sig)
        return rep, out

    # -- async (pipelined) issue path -------------------------------------

    def _keyed_call_async(
        self, cmd: str, keys: np.ndarray, arrays: Arrays, **fields
    ):
        """Async twin of ``_keyed_call``: issues the request onto the
        client's pipelined window and returns a Future of (rep, arrays).
        The need_keys bounce re-issues with the SAME "k<n>" seq from the
        completion callback (``_urgent``: a reader thread must not block
        on window space it is responsible for freeing), and a connection
        that outlives the client's own heal window falls back to the
        blocking resolver retry loop on the handle's recovery thread."""
        outer: Future = Future()
        lseq = f"k{next(self._kseq)}"
        sig = _sig(keys)
        send_keys = not (self._key_caching and sig in self._sent_sigs)
        payload = dict(arrays)
        if send_keys:
            payload["keys"] = keys.astype(self._key_dtype)

        def on_reply(f, bounced: bool = False) -> None:
            # NOTHING may escape this callback: concurrent.futures logs
            # and swallows done-callback exceptions, which would leave
            # ``outer`` unresolved and its waiter parked forever — every
            # failure (including a shut-down recovery pool or a closed
            # client on the bounce re-issue) must land in ``outer``
            try:
                try:
                    rep, out = f.result()
                except (ConnectionError, BrokenPipeError, OSError):
                    if self._resolve_addr is None:
                        raise
                    # server moved or kept resetting past the client's
                    # heal: run the blocking resolver loop OFF this
                    # (reader) thread, same lseq so every delivery stays
                    # one dedup identity
                    self._recovery().submit(
                        self._recover_async, cmd, keys, arrays, lseq,
                        fields, outer,
                    )
                    return
                if rep.get("need_keys"):
                    if bounced:  # keys were in the frame: a repeat is a bug
                        raise RuntimeError(
                            f"server rank {self.rank} bounced a keyed {cmd}"
                        )
                    p2 = dict(arrays)
                    p2["keys"] = keys.astype(self._key_dtype)
                    f2 = self.client.call_async(
                        cmd, arrays=p2, worker=self.worker, sig=sig,
                        zip=self._zip, _seq=lseq, _urgent=True, **fields,
                    )
                    f2.add_done_callback(lambda g: on_reply(g, bounced=True))
                    return
                self._sent_sigs.put(sig)
                outer.set_result((rep, out))
            except BaseException as e:  # noqa: BLE001 — future boundary
                if not outer.done():
                    outer.set_exception(e)

        try:
            f1 = self.client.call_async(
                cmd, arrays=payload, worker=self.worker, sig=sig,
                zip=self._zip, _seq=lseq, **fields,
            )
        except (ConnectionError, BrokenPipeError, OSError) as e:
            if self._resolve_addr is None:
                raise
            self._recovery().submit(
                self._recover_async, cmd, keys, arrays, lseq, fields, outer
            )
            return outer
        f1.add_done_callback(on_reply)
        return outer

    def _recover_async(
        self, cmd, keys, arrays, lseq, fields, outer
    ) -> None:
        """Recovery-thread tail of a failed async call: the synchronous
        resolver retry loop, completing the caller's outer future."""
        try:
            outer.set_result(
                self._keyed_call(cmd, keys, arrays, lseq=lseq, **fields)
            )
        except BaseException as e:  # noqa: BLE001 — future boundary
            outer.set_exception(e)

    def _recovery(self) -> ThreadPoolExecutor:
        with self._pool_lock:  # NOT _reconnect_lock: see __init__
            if self._recovery_pool is None:
                self._recovery_pool = ThreadPoolExecutor(
                    max_workers=1,
                    thread_name_prefix=f"ps-recover-{self.rank}",
                )
            return self._recovery_pool

    def pull_async(self, local_keys: np.ndarray):
        """Issue a pull without blocking; Future of the float32 rows. Flow
        events link the issue span to the completion across the window.
        Serving handles consult the key cache first — a fresh entry
        resolves the future immediately with zero wire traffic."""
        out_f: Future = Future()
        if len(local_keys) == 0:
            out_f.set_result(np.zeros(0, dtype=np.float32))
            return out_f
        extra: dict[str, Any] = {}
        sig = ent = None
        own = False
        gen = None
        if self._kcache is not None:
            vals, extra, sig, ent, own, gen = self._cache_try(local_keys)
            if vals is not None:
                out_f.set_result(vals)
                return out_f
        try:
            with trace.span(
                "ps.pull", cat="ps", rank=self.rank, keys=len(local_keys)
            ):
                flow = trace.flow_start("ps.pull.inflight", cat="ps")
                ctx = trace.wire_context()
                inner = self._keyed_call_async(
                    "pull", local_keys, {}, **self._pull_fields(), **extra
                )
        except BaseException:
            if own:
                self._kcache.end_refresh(sig)
            raise

        def done(f) -> None:
            # nothing may escape (see _keyed_call_async.on_reply): a
            # swallowed callback error would leave out_f unresolved and
            # its waiter parked forever
            try:
                with trace.activate(ctx):
                    trace.flow_end(
                        "ps.pull.inflight", cat="ps", flow_id=flow
                    )
                rep, out = f.result()
                if self._kcache is not None:
                    out_f.set_result(
                        self._cache_settle(
                            rep, out, local_keys, sig, ent, own, gen
                        )
                    )
                else:
                    out_f.set_result(self._decode_pull(out))
            except BaseException as e:  # noqa: BLE001 — future boundary
                if own:
                    self._kcache.end_refresh(sig)  # idempotent release
                if not out_f.done():
                    out_f.set_exception(e)

        inner.add_done_callback(done)
        return out_f

    def push_async(self, local_keys: np.ndarray, grads: np.ndarray):
        """Issue a push without blocking; the Future resolves (to None)
        once the server acked the apply — the worker's PushWindow hangs
        ssp_finish off that. A flow event pair links the issue span to
        the completion event so Perfetto draws the in-flight arrow."""
        done_f: Future = Future()
        if len(local_keys) == 0:
            done_f.set_result(None)
            return done_f
        fields, arrays = self._encode_push(local_keys, grads)
        with trace.span(
            "ps.push", cat="ps", rank=self.rank, keys=len(local_keys),
            bytes=int(sum(a.nbytes for a in arrays.values())),
        ):
            flow = trace.flow_start("ps.push.inflight", cat="ps")
            ctx = trace.wire_context()
            inner = self._keyed_call_async(
                "push", local_keys, arrays, **fields
            )

        def done(f) -> None:
            # nothing may escape (see _keyed_call_async.on_reply)
            try:
                with trace.activate(ctx):
                    trace.flow_end(
                        "ps.push.inflight", cat="ps", flow_id=flow
                    )
                f.result()
                if self._kcache is not None:
                    # second, ACK-time invalidation: the server defers
                    # the ack until the batched apply published, so a
                    # pull raced between the encode-time invalidation
                    # and this ack may have re-cached the PRE-apply
                    # snapshot — drop it now, and read-your-writes holds
                    # from the moment this future resolves
                    self._kcache.invalidate_keys(local_keys, rank=self.rank)
                done_f.set_result(None)
            except BaseException as e:  # noqa: BLE001 — future boundary
                if not done_f.done():
                    done_f.set_exception(e)

        inner.add_done_callback(done)
        return done_f

    # -- error-feedback accumulator (quantized transport) ------------------

    #: above this many rows the accumulator switches from a dense
    #: range-indexed array to a compact touched-keys-only map — a sparse
    #: workload on a 10^9-key shard must not allocate the whole range
    #: client-side just because one high key was pushed
    _DENSE_RESIDUAL_ROWS = 1 << 22

    def _res_rows(self, keys: np.ndarray, vdim: int) -> np.ndarray:
        """Row indices into the residual buffer for ``keys``, allocating
        as needed (caller holds ``_res_lock``). Small known ranges index
        the buffer by the range-relative key directly (vectorized);
        large or unknown ranges go through a compact key->row map, so
        memory is bounded by TOUCHED keys, never the range."""
        if self._residual is None or self._res_vdim != vdim:
            self._residual = np.zeros((0, vdim), np.float32)
            self._res_vdim = vdim
            self._res_map = (
                None
                if 0 < self._res_range <= self._DENSE_RESIDUAL_ROWS
                else {}
            )
        if self._res_map is None:
            rows = keys
            hi = int(keys.max()) + 1 if len(keys) else 0
        else:
            m = self._res_map
            rows = np.empty(len(keys), np.int64)
            for i, k in enumerate(keys.tolist()):
                j = m.get(k)
                if j is None:
                    j = m[k] = len(m)
                rows[i] = j
            hi = len(m)
        if hi > len(self._residual):
            grown = np.zeros(
                (max(hi, 2 * len(self._residual)), vdim), np.float32
            )
            grown[: len(self._residual)] = self._residual
            self._residual = grown
        return rows

    def residual_rows(self, keys: np.ndarray) -> np.ndarray:
        """Current residual rows for ``keys``, zeros where nothing
        accumulated (observability + the tests' telescoping identity).
        Strictly READ-ONLY: unlike ``_res_rows`` it never allocates map
        entries or grows the buffer — a metrics loop sweeping the key
        space must not inflate the accumulator it is observing."""
        with self._res_lock:
            if self._residual is None:
                return np.zeros((len(keys), 1), np.float32)
            out = np.zeros((len(keys), self._res_vdim), np.float32)
            if self._res_map is None:
                known = keys < len(self._residual)
                out[known] = self._residual[keys[known]]
            else:
                m = self._res_map
                for i, k in enumerate(keys.tolist()):
                    j = m.get(k)
                    if j is not None:
                        out[i] = self._residual[j]
            return out

    def residual_norm(self) -> float:
        """Mean |residual| over allocated rows (observability + tests)."""
        with self._res_lock:
            if self._residual is None:
                return 0.0
            n = (
                len(self._res_map)
                if self._res_map is not None
                else len(self._residual)
            )
            if n == 0:
                return 0.0
            return float(np.abs(self._residual[:n]).mean())

    def _encode_push(
        self, local_keys: np.ndarray, grads: np.ndarray
    ) -> tuple[dict[str, Any], Arrays]:
        """Apply the send filters to one push payload (shared by the sync
        and async paths): the negotiated per-segment quantized codec with
        error feedback, the legacy fixed-point filter, else f32.

        Called exactly once per LOGICAL push — transport resends, the
        need_keys bounce and the keyed-seq recovery path all reuse the
        returned arrays — so the residual fold below happens exactly once
        however chaotic the wire gets."""
        if self._kcache is not None:
            # exact self-invalidation (serving handles): this handle must
            # never read its own write stale out of its own cache. Done
            # at encode time — once per logical push — though dropping a
            # cache entry twice would be harmless anyway.
            self._kcache.invalidate_keys(local_keys, rank=self.rank)
        fields: dict[str, Any] = {"codec": 0}
        g = grads.astype(np.float32, copy=False).reshape(len(local_keys), -1)
        if self._quant_bytes and "qwire" in self.client.peer_features:
            with self._res_lock:
                rows = self._res_rows(local_keys, g.shape[1])
                g_tot = g + self._residual[rows]
                q, qs = self._quantizer.encode(next(self._quant_seed), g_tot)
                res = g_tot - self._quantizer.decode(q, qs).reshape(
                    g_tot.shape
                )
                self._residual[rows] = res
            arrays: Arrays = {"q": q, "qs": qs}
            fields["codec"] = self._quant_bytes
            fields["qseg"] = self._quantizer.seg
            wire_counters.inc(
                "wire_quant_bytes_saved",
                max(int(g_tot.nbytes) - q.nbytes - qs.nbytes, 0),
            )
            # residual-norm gauge (micro-units, cluster-merged as a max):
            # a growing peak means quantization error is accumulating
            # faster than error feedback drains it
            wire_counters.observe_max(
                "wire_quant_residual_peak",
                int(np.abs(res).mean() * 1e6),
            )
        elif self._quant_bytes:
            # quant configured but the peer never acked "qwire" (old or
            # downgraded server, or the pre-negotiation first frames):
            # float path — flushing any residual accumulated before a
            # downgrade so no gradient mass is ever stranded
            with self._res_lock:
                if self._residual is not None and len(self._residual):
                    rows = self._res_rows(local_keys, g.shape[1])
                    g = g + self._residual[rows]  # fresh buffer
                    self._residual[rows] = 0.0
                else:
                    g = np.array(g, dtype=np.float32)  # own the buffer
            arrays = {"g": g}
        elif self._codec_bytes:
            import jax

            e = self._codec.encode(
                jax.random.key(next(self._quant_seed)),
                grads.astype(np.float32),
            )
            arrays = {
                "q": np.asarray(e.q),
                "lo": np.asarray(e.lo)[None],
                "scale": np.asarray(e.scale)[None],
            }
            fields["codec"] = self._codec_bytes
        else:
            # own the buffer (np.array always copies): the async pipeline
            # serializes at send — and heal RESEND — time, so aliasing
            # the caller's gradient array would let a reused buffer
            # silently corrupt an in-flight push
            arrays = {"g": np.array(g, dtype=np.float32)}
        # push payload accounting (pre-compression, keys excluded): the
        # bench's wire-bytes ratio divides the float-path total by the
        # quantized-path total on identical workloads
        wire_counters.inc(
            "wire_push_payload_bytes",
            sum(int(a.nbytes) for a in arrays.values()),
        )
        return fields, arrays

    # -- quantized pull (read-mostly traffic) ------------------------------

    def _pull_fields(self) -> dict[str, Any]:
        """Extra pull request fields: ask for quantized rows only once
        the peer negotiated the codec ([wire] quant_pull)."""
        if self._quant_pull and "qwire" in self.client.peer_features:
            return {"quant": self._quant_bytes, "qseg": self._quantizer.seg}
        return {}

    def _decode_pull(self, out: Arrays) -> np.ndarray:
        """Decode one pull reply: quantized rows when the server sent
        them, the float fallback otherwise (a non-quant server ignores
        the ``quant`` field and replies floats — degrade, not corrupt)."""
        if "q" in out:
            return self._quantizer.decode(out["q"], out["qs"])
        return out["w"].astype(np.float32)

    # -- client-side versioned key cache (serving handles only) -----------

    def _book_serve_age(self, age_us: float, src: str) -> None:
        """Book the realized data age ONE serve handed its consumer
        (freshness plane, ISSUE 17): the global ``serve.age_s``
        histogram (what `cli top`'s age column and the ``pull_age_ms``
        SLO read; the pre-rename name ``serve.age`` stays a read-side
        alias for beats from older nodes — utils/timeseries.py),
        this handle's per-range matrix when it knows its range, and the
        flight recorder (a shed-stale serve near the staleness ceiling
        is exactly the context a postmortem wants on the timeline)."""
        age_s = max(float(age_us), 0.0) / 1e6
        latency_histograms.observe("serve.age_s", age_s)
        if self._range_scope is not None:
            self._range_scope.age(age_s)
        flightrec.record(
            "freshness.serve", rank=self.rank, src=src,
            age_us=int(age_us),
        )

    def _cache_try(
        self, local_keys: np.ndarray
    ) -> tuple[np.ndarray | None, dict[str, Any], str, Any, bool, int]:
        """Consult the key cache for one pull: (locally served rows or
        None, extra wire fields for the revalidation, sig, entry, owns-
        refresh). A fresh entry short-circuits the wire entirely; a
        stale one turns the pull into an ``if_newer`` revalidation —
        claimed single-flight, so while one caller refreshes, concurrent
        pulls of the same keys serve the bounded-stale rows instead of
        duplicating the wire refresh. ``shed_ok`` is advertised only
        while the entry is within the hard staleness ceiling (an
        overloaded server can never stretch us past it); a caller that
        got the refresh claim MUST settle it via ``_cache_settle`` or
        ``end_refresh`` on the error path. The final element is the
        cache's invalidation generation AT ISSUE: ``_cache_settle``
        hands it to ``put`` so rows that crossed a concurrent push on
        the wire are never installed over that push's invalidation."""
        # (rank, digest) composite: keys are range-relative, so a shared
        # multi-shard frontend cache must namespace entries by shard —
        # two shards produce the same digest for different rows
        sig = (self.rank, _sig(local_keys))
        gen = self._kcache.gen
        ent = self._kcache.lookup(sig)
        if ent is None:
            wire_counters.inc("serve_cache_misses")
            # sv: ask for the reply's version stamp (rides the JSON
            # tail; if_newer implies it on the revalidation paths below)
            return None, {"sv": 1}, sig, None, False, gen
        if self._kcache.fresh(ent):
            wire_counters.inc("serve_cache_hits")
            self._book_serve_age(ent.age_us(), "cache")
            # a copy, not the cached buffer: callers own their rows and
            # may scribble on them; the cache must stay pristine
            return ent.values.copy(), {}, sig, ent, False, gen
        if not self._kcache.begin_refresh(sig):
            if self._kcache.can_shed(ent):
                # another thread's refresh is in flight: serve the
                # bounded-stale rows rather than duplicate its RTT
                wire_counters.inc("serve_cache_stale_hits")
                self._book_serve_age(ent.age_us(), "stale")
                return ent.values.copy(), {}, sig, ent, False, gen
            # past the staleness ceiling: correctness wins — do our own
            # wire pull alongside the in-flight refresh
            fields: dict[str, Any] = {"if_newer": ent.version}
            return None, fields, sig, ent, False, gen
        fields = {"if_newer": ent.version}
        if self._kcache.can_shed(ent):
            fields["shed_ok"] = 1
        return None, fields, sig, ent, True, gen

    def _cache_settle(
        self, rep: dict[str, Any], out: Arrays,
        local_keys: np.ndarray, sig: str, ent, own: bool = False,
        gen: int | None = None,
    ) -> np.ndarray:
        """Interpret one pull reply against the cache and return the
        rows. ``ent`` is the entry reference captured at issue time: a
        concurrent invalidation doesn't invalidate THIS read (the read
        was validated against a snapshot that preceded the push), it
        only stops the entry from being revalidated in place. ``own``
        releases this pull's single-flight refresh claim."""
        try:
            age = rep.get("_age_us")  # server-measured realized age
            if rep.get("not_modified") and ent is not None:
                if rep.get("shed"):
                    # the server shed our revalidation: keep serving the
                    # cached rows (we only advertised shed_ok while
                    # inside max_stale) and back off for retry_after
                    wire_counters.inc("serve_shed_served")
                    self._kcache.shed_backoff(
                        sig, float(rep.get("retry_after_ms", 20)) / 1e3
                    )
                    # no age echo on a shed reply (nothing validated):
                    # the realized age is the entry's own, still growing
                    self._book_serve_age(ent.age_us(), "shed")
                else:
                    self._kcache.revalidated(
                        sig, int(rep["ver"]), age_us=age,
                    )
                    self._book_serve_age(
                        age if age is not None else ent.age_us(),
                        "revalidate",
                    )
                return ent.values.copy()
            vals = self._decode_pull(out)
            ver = rep.get("ver")
            if ver is not None:
                # as_of: an invalidation (a concurrent push) since this
                # pull was issued wins — the install is skipped rather
                # than resurrect possibly pre-push rows
                self._kcache.put(
                    sig, local_keys, vals, int(ver), as_of=gen,
                    rank=self.rank, age_us=age,
                )
                if age is not None:
                    self._book_serve_age(age, "pull")
            return vals
        finally:
            if own:
                self._kcache.end_refresh(sig)

    def pull(self, local_keys: np.ndarray) -> np.ndarray:
        if len(local_keys) == 0:
            return np.zeros(0, dtype=np.float32)
        extra: dict[str, Any] = {}
        sig = ent = None
        own = False
        gen = None
        if self._kcache is not None:
            vals, extra, sig, ent, own, gen = self._cache_try(local_keys)
            if vals is not None:
                return vals  # served locally: zero wire traffic
        try:
            with trace.span(
                "ps.pull", cat="ps", rank=self.rank, keys=len(local_keys)
            ) as sp:
                rep, out = self._keyed_call(
                    "pull", local_keys, {}, **self._pull_fields(), **extra
                )
                sp.set(bytes=int(sum(a.nbytes for a in out.values())))
        except BaseException:
            if own:
                self._kcache.end_refresh(sig)
            raise
        if self._kcache is not None:
            return self._cache_settle(
                rep, out, local_keys, sig, ent, own, gen
            )
        return self._decode_pull(out)

    def push(self, local_keys: np.ndarray, grads: np.ndarray) -> None:
        if len(local_keys) == 0:
            return
        fields, arrays = self._encode_push(local_keys, grads)
        with trace.span(
            "ps.push", cat="ps", rank=self.rank, keys=len(local_keys),
            bytes=int(sum(a.nbytes for a in arrays.values())),
        ):
            self._keyed_call("push", local_keys, arrays, **fields)
        if self._kcache is not None:
            # ack-time invalidation (see push_async.done): a pull that
            # raced the deferred apply may have re-cached pre-push rows
            self._kcache.invalidate_keys(local_keys, rank=self.rank)

    def dump(self) -> tuple[int, np.ndarray]:
        rep, out = self.client.call("dump")
        return int(rep["begin"]), out["w"]

    def stats(self) -> dict[str, Any]:
        rep, _ = self.client.call("stats")
        return {k: v for k, v in rep.items() if k != "ok"}

    def shutdown(self) -> None:
        self.client.call("shutdown")

    def close(self) -> None:
        watchdog.unregister(self._wd_name)
        self.client.close()
        if self._recovery_pool is not None:
            self._recovery_pool.shutdown(wait=False)


# ---------------------------------------------------------------------------
# node entry points (ref: main.cc role dispatch; spawned by launch_local or
# the `cli node` subcommand — one process per node, like script/local.sh)
# ---------------------------------------------------------------------------


def _export_witness_env(child_env: dict) -> None:
    """Arm the runtime lock-order witness in spawned children whenever
    THIS process runs under it — whether it was armed by the
    ``PS_LOCK_WITNESS`` env var (already inherited via the env copy) or
    by an explicit ``witness.install()`` (the tier-1 conftest), which an
    env copy alone would silently fail to propagate. Children arm at
    package import (parallel/__init__), so every lock a spawned node
    constructs is order-checked too. The lockset race witness rides the
    same rule: an armed parent spawns armed children, so the
    registered shared objects of every node in a launch_local cluster
    are lockset-checked."""
    from parameter_server_tpu.analysis import racewitness, witness

    if witness.installed():
        child_env[witness.ENV_VAR] = "1"
    if racewitness.installed():
        child_env[racewitness.ENV_VAR] = "1"


class _RemoteBeatSink:
    """Adapter giving ``HeartbeatReporter`` a coordinator RPC sink.

    Opens its OWN connection: the node's main ControlClient serializes
    calls under a lock and legitimately parks for long stretches
    (blocking kv_get, ssp_wait) — beats riding that lock would stall and
    read as a dead node exactly when the node is merely waiting."""

    def __init__(self, scheduler: str):
        self._scheduler = scheduler
        # short retry window: a beat is periodic — retrying one for longer
        # than the beat interval just delays the NEXT (fresher) beat
        self._ctl: ControlClient | None = ControlClient(
            scheduler, reconnect_timeout_s=1.0
        )

    def beat(self, node_id: int, stats: dict | None = None) -> bool:
        # a single transient socket failure must not silence beats forever
        # (a healthy node would read as dead): drop the connection and
        # rebuild it on the next beat. Returns delivery success so the
        # reporter knows whether to ack the audit-spool batches the beat
        # carried (False = they stay in flight for the next beat).
        try:
            if self._ctl is None:
                self._ctl = ControlClient(
                    self._scheduler, retries=1, retry_delay=0.0,
                    reconnect_timeout_s=1.0,
                )
            self._ctl.beat(node_id, stats)
            return True
        except Exception:
            if self._ctl is not None:
                self._ctl.close()
            self._ctl = None
            return False

    def close(self) -> None:
        if self._ctl is not None:
            self._ctl.close()


class _Beats:
    """A node's liveness heartbeat: HeartbeatReporter over a dedicated
    coordinator connection (ref: the reference's heartbeat thread —
    liveness must not depend on training cadence). Each beat piggybacks
    this process's telemetry snapshot (counters + latency histograms +
    named timers), which is what the coordinator's ``telemetry`` command
    merges into the cluster view — no second collection path."""

    def __init__(
        self,
        scheduler: str,
        node_id: int,
        interval_s: float,
        audit_cfg: "AuditConfig | None" = None,
    ):
        self._sink = _RemoteBeatSink(scheduler)
        # audit plane (ISSUE 14): heartbeating nodes arm the flightrec
        # event spool so their protocol-invariant events (push acks,
        # apply commits, RCU publishes, heals, sheds) ride every beat to
        # the coordinator's streaming auditor; the reporter drains/acks
        self._armed_spool = False
        if audit_cfg is not None and audit_cfg.enabled:
            flightrec.configure_spool(
                audit_cfg.spool_capacity, audit_cfg.batch_events
            )
            self._armed_spool = True

        def beat_stats() -> dict:
            # ONE snapshot serves three planes (ISSUE 13): the beat
            # piggyback, this node's local time-series ring roll, and
            # the heartbeat payload guard's saturation caps
            from parameter_server_tpu.utils.timeseries import beat_telemetry

            return {**host_stats(), "telemetry": beat_telemetry()}

        self._rep = HeartbeatReporter(
            self._sink, node_id, interval_s, stats_fn=beat_stats
        )
        self._rep.start()
        # watchdog: heartbeat silence, seen from INSIDE the silent node —
        # the beat thread is always "busy" (liveness is its whole job),
        # so a beats counter that stops advancing is a wedged reporter
        self._wd_name = f"heartbeat:{node_id}"
        watchdog.register(
            self._wd_name, lambda: (True, self._rep.beats),
            thread_name="ps-heartbeat",
        )

    def stop(self) -> None:
        watchdog.unregister(self._wd_name)
        self._rep.stop()
        self._sink.close()
        if self._armed_spool:
            flightrec.configure_spool(None)


def run_server(
    cfg: PSConfig,
    scheduler: str,
    rank: int,
    num_servers: int,
    bind_host: str = "127.0.0.1",
    advertise_host: str = "",
    ckpt_dir: str = "",
) -> None:
    """One server process. ``bind_host="0.0.0.0"`` + a routable
    ``advertise_host`` lets workers on other hosts connect (the default
    loopback pair only serves the single-host multi-process harness).

    ``ckpt_dir`` enables recovery (ref: each server dumps its own range;
    resume = reload it): an existing dump for this range is loaded on
    startup (a relaunched server resumes where its last dump left off),
    and with fault.server_ckpt_interval_s > 0 the state is re-dumped
    periodically while serving."""
    from parameter_server_tpu.models.linear import updater_from_config

    ranges = KeyRange(0, cfg.data.num_keys).even_divide(num_servers)
    srv = ShardServer(
        updater_from_config(cfg),
        ranges[rank],
        host=bind_host,
        advertise_host=advertise_host,
        fault_plan=_plan_from_cfg(cfg),
        server_cfg=cfg.server,
        serve_cfg=cfg.serve,
    )
    if ckpt_dir:
        if srv.load_state(ckpt_dir):
            print(f"[server {rank}] resumed from {ckpt_dir}", flush=True)
        if cfg.fault.server_ckpt_interval_s > 0:
            srv.start_checkpointing(ckpt_dir, cfg.fault.server_ckpt_interval_s)
    ctl = ControlClient(
        scheduler, reconnect_timeout_s=cfg.fault.reconnect_timeout_s
    )
    node_id = ctl.register("server", rank=rank)
    # set AFTER any resume: workers re-resolving this key must never beat
    # the state load and pull pre-resume zeros
    ctl.kv_set(f"server_addr/{rank}", addr=srv.address)
    beats = _Beats(
        scheduler, node_id, cfg.fault.heartbeat_interval_s,
        audit_cfg=cfg.audit,
    )
    srv.serve_forever()  # until the scheduler's shutdown
    if ckpt_dir:
        srv.stop_checkpointing()  # no periodic writer behind the final dump
        srv.save_state(ckpt_dir)
    beats.stop()
    ctl.close()
    trace.tracer.flush()  # export this process's spans (no-op if disabled)


def _connect_servers(
    ctl: ControlClient, worker_rank: int, num_servers: int, cfg: PSConfig
) -> list[ServerHandle]:
    ranges = KeyRange(0, cfg.data.num_keys).even_divide(num_servers)
    handles = []
    for s in range(num_servers):
        fields, _ = ctl.kv_get(f"server_addr/{s}", block=True, timeout=60)

        def resolve(s=s) -> str:
            # re-read the registry: a relaunched server re-publishes its
            # (new) address under the same rank key
            f, _ = ctl.kv_get(f"server_addr/{s}", block=True, timeout=10)
            return f["addr"]

        handles.append(
            ServerHandle(
                fields["addr"], s, worker_rank, cfg,
                range_size=ranges[s].size, key_range=ranges[s],
                resolve_addr=resolve,
                # the TRAINING tier: never a serving handle. A trainer's
                # staleness contract is the SSP clock (bounded delay in
                # steps), and a TTL cache would stack a second, time-based
                # staleness on top of it — so training pulls always hit
                # the wire even when [serve] cache is on for this config.
                serving=False,
            )
        )
    return handles


def run_worker(
    cfg: PSConfig,
    scheduler: str,
    rank: int,
    num_servers: int,
    report_interval: int = 20,
) -> None:
    """The async-SGD worker loop over the wire (ref: AsyncSGDWorker)."""
    import jax

    from parameter_server_tpu.data.reader import MinibatchReader
    from parameter_server_tpu.models import metrics as M
    from parameter_server_tpu.ops.sparse import csr_grad, csr_logits, logistic_loss

    ctl = ControlClient(
        scheduler, reconnect_timeout_s=cfg.fault.reconnect_timeout_s
    )
    node_id = ctl.register("worker", rank=rank)
    beats = _Beats(
        scheduler, node_id, cfg.fault.heartbeat_interval_s,
        audit_cfg=cfg.audit,
    )
    # the scheduler's ssp_init/workload_init must land before our first
    # fetch; registration order doesn't guarantee it, this kv flag does
    ctl.kv_get("scheduler_init_done", block=True, timeout=120)
    servers = _connect_servers(ctl, rank, num_servers, cfg)
    ranges = KeyRange(0, cfg.data.num_keys).even_divide(num_servers)
    # the transport-neutral data plane (parallel/backend.py): this loop
    # only ever sees global keys; the backend owns the range fan-out
    # (slice against server ranges, concurrent per-shard wire calls,
    # merge) that used to be hand-rolled here
    from parameter_server_tpu.parallel.backend import SocketBackend

    backend = SocketBackend(
        servers, ranges, cfg.data.num_keys, own_handles=False
    )
    from parameter_server_tpu.data.batch import training_builder

    builder = training_builder(cfg)

    @jax.jit
    def grad_step(w_u, values, local_ids, row_ids, labels, mask):
        logits = csr_logits(
            w_u, values, local_ids, row_ids, num_rows=labels.shape[0]
        )
        loss, err = logistic_loss(logits, labels, mask)
        g = csr_grad(err, values, local_ids, row_ids, num_unique=w_u.shape[0])
        return loss, jax.nn.sigmoid(logits), g

    from parameter_server_tpu.parallel.ssp import PushWindow

    # in-flight push bound, in whole steps: the SSP delay shapes it (a step
    # only ssp_finishes when its pushes applied, so more than tau+1 steps
    # in flight could never clear the gate anyway), and the explicit
    # wire.max_inflight_pushes knob tightens it when wire memory — not
    # staleness — is the binding constraint
    max_delay = cfg.solver.max_delay
    ssp_limit = max_delay if max_delay >= 0 else (1 << 30)
    cap = cfg.wire.max_inflight_pushes
    inflight_limit = ssp_limit if cap <= 0 else min(ssp_limit, cap)
    pushes = PushWindow(
        inflight_limit, retire=lambda step_i: ctl.ssp_finish(rank, step_i)
    )

    step = 0
    window: list[tuple[float, np.ndarray, np.ndarray]] = []
    t0 = time.perf_counter()
    ex_seen = 0

    def flush_window() -> None:
        """Send the window's merged Progress (ref: per-report_interval
        Progress protos merged at the scheduler)."""
        nonlocal window, t0
        if not window:
            return
        n = sum(len(y) for _, _, y in window)
        y = np.concatenate([y for _, _, y in window])
        p = np.concatenate([pr for _, pr, _ in window])
        ctl.progress(
            rank,
            {
                "examples": n,
                "examples_total": ex_seen,
                "objv": sum(l for l, _, _ in window) / n,
                "auc": M.auc(y, p),
                "ex_per_sec": n / max(time.perf_counter() - t0, 1e-9),
                # MEASURED wire traffic, cumulative for this worker (ref:
                # the Postoffice per-message byte counters) — merged at the
                # scheduler as a sum over workers. Counted at the FRAME
                # layer (send_frame/recv_frame), so control, heartbeat and
                # data-plane traffic are all in
                "wire_bytes_out": wire_counters.get("wire_bytes_out"),
                "wire_bytes_in": wire_counters.get("wire_bytes_in"),
                # adaptive-compression accounting (the per-filter byte
                # counters the reference's Postoffice kept): bytes the
                # codec won, and probes that declined incompressible data
                "wire_bytes_saved": wire_counters.get("wire_bytes_saved"),
                "wire_comp_skipped": wire_counters.get("wire_comp_skipped"),
                # self-healing counters, cumulative for this worker process
                # (merged at the scheduler as cluster totals)
                "rpc_retries": wire_counters.get("rpc_retries"),
                "rpc_reconnects": wire_counters.get("rpc_reconnects"),
            },
        )
        window = []
        t0 = time.perf_counter()

    while True:
        with trace.span("step.workload_fetch", cat="step"):
            workload = ctl.workload_fetch(rank)
        if workload is None:
            if ctl.workload_all_done():
                break
            # nothing pending, but another worker still holds active
            # shards — if it dies the scheduler requeues them, so keep
            # polling instead of exiting (ref: the pool is drained only
            # when every shard is FINISHED, not merely assigned)
            time.sleep(0.2)
            continue
        _epoch, path = workload.split(":", 1)
        for b in MinibatchReader([path], cfg.data.format, builder):
            # retire our own in-flight pushes first: the clock's gate for
            # step t includes this worker's finished counter (wait_time
            # semantics), so draining after the gate would self-deadlock
            pushes.gate()
            # step anatomy (the "where did this step's 40 ms go" spans):
            # one enclosing step span; ssp_wait / pull / compute are its
            # children. Pull and push fan out over every shard server
            # CONCURRENTLY on the pipelined async wire — no thread pool;
            # flow events tie each push's issue span to its completion.
            with trace.span("step", cat="step", step=step):
                with trace.span("step.ssp_wait", cat="step"):
                    ctl.ssp_wait(rank, step)
                # the batch's (sorted) unique GLOBAL keys; the backend
                # does the range slicing + concurrent per-shard wire
                real = b.unique_keys[1 : b.num_unique]
                with trace.span("step.pull", cat="step"):
                    pulled = backend.pull(real)
                with trace.span("step.compute", cat="step"):
                    w_u = np.zeros(len(b.unique_keys), dtype=np.float32)
                    w_u[1 : b.num_unique] = pulled.ravel()
                    loss, probs, g = grad_step(
                        w_u, b.values, b.local_ids, b.row_ids, b.labels,
                        b.example_mask,
                    )
                    g_real = np.asarray(g).ravel()[1 : b.num_unique]
                # pushes stay in flight past this span's exit; the flow
                # links (ps.push.inflight) bridge issue to completion
                futs = [backend.push_async(real, g_real)]
            pushes.add(step, futs)
            ex_seen += b.num_examples
            window.append(
                (
                    float(loss),
                    np.asarray(probs)[: b.num_examples],
                    b.labels[: b.num_examples],
                )
            )
            if len(window) >= report_interval:
                flush_window()
            step += 1
        ctl.workload_finish(workload)
    pushes.wait_all()  # the sync point: every in-flight push acked
    flush_window()
    ctl.ssp_retire(rank)  # out of data: stop gating the still-running workers
    # completion signal (replaces a fixed barrier: a barrier over
    # num_workers+1 can never release once a worker dies — the scheduler's
    # monitor loop instead waits for every rank to be done-or-dead)
    ctl.kv_set(f"worker_done/{rank}")
    beats.stop()
    for sh in servers:
        sh.close()
    ctl.close()
    trace.tracer.flush()  # export this process's spans (no-op if disabled)


def run_scheduler(
    cfg: PSConfig,
    coordinator: Coordinator,
    num_servers: int,
    num_workers: int,
    model_out: str = "",
) -> dict[str, Any]:
    """Drive a run: init pools/clock, wait for completion, assemble the
    model from server dumps (ref: SaveModel, each server writes its range),
    evaluate, shut everything down."""
    ctl = ControlClient(coordinator.address)
    ctl.register("scheduler")
    ctl.ssp_init(num_workers, cfg.solver.max_delay)
    items = [
        f"{e}:{f}" for e in range(max(cfg.solver.epochs, 1)) for f in cfg.data.files
    ]
    ctl.workload_init(items)
    ctl.kv_set("scheduler_init_done")  # workers block on this before fetching
    if cfg.fault.recovery_sweep_interval_s > 0:
        # dead-WORKER recovery (requeue + clock release) runs inside the
        # coordinator's sweep thread; this loop just records its verdicts.
        # Dead-SERVER policy (grace window / fail fast) stays here — it
        # needs run-level knowledge (checkpointing on? abort or wait?)
        coordinator.start_recovery(cfg.fault.recovery_sweep_interval_s)

    # Monitor loop (ref: the scheduler's dead-node handling): wait until
    # every worker rank is done or dead. A plain barrier cannot do this —
    # it would park forever on the dead worker's missing arrival.
    dead_ranks: set[int] = set()
    server_dead_since: dict[int, float] = {}  # rank -> first seen dead
    t_start = time.monotonic()

    def declare_dead(r: int, why: str) -> None:
        requeued = ctl.workload_reassign(worker=r)
        ctl.ssp_retire(r)
        dead_ranks.add(r)
        print(
            f"[scheduler] worker {r} {why}; requeued {len(requeued)} "
            f"shard(s), retired its clock",
            flush=True,
        )

    while True:
        done = {
            r
            for r in range(num_workers)
            if ctl.kv_get(f"worker_done/{r}") is not None
        }
        if done | dead_ranks >= set(range(num_workers)):
            break
        for r, info in ctl.recovered_workers().items():
            if r not in dead_ranks:
                dead_ranks.add(r)
                print(
                    f"[scheduler] worker {r} dead (missed heartbeats); "
                    f"sweep requeued {len(info['requeued'])} shard(s) and "
                    "retired its clock",
                    flush=True,
                )
        registry = ctl.nodes()
        dead_ids, _alive = ctl.dead_nodes()
        dead_set = {int(x) for x in dead_ids}
        alive_server_ranks = {
            int(n["rank"])
            for nid2, n in registry.items()
            if n.get("role") == "server"
            and "rank" in n
            and int(nid2) not in dead_set
        }
        for nid in dead_ids:
            info = registry.get(str(nid), {})
            role = info.get("role")
            if role == "server":
                r = int(info.get("rank", -1))
                grace = cfg.fault.server_restart_grace_s
                if r in alive_server_ranks:
                    # a replacement re-registered under this rank (resume
                    # from its checkpoint); the old corpse can be ignored
                    server_dead_since.pop(r, None)
                    continue
                now = time.monotonic()
                since = server_dead_since.setdefault(r, now)
                if grace <= 0 or now - since > grace:
                    # without checkpoint-backed restart a dead server is
                    # unrecoverable (its key range is gone): fail fast with
                    # the cause instead of letting workers hang on its
                    # socket until the launcher timeout
                    raise RuntimeError(
                        f"shard server rank {r} died (missed heartbeats) "
                        + (
                            f"and no replacement registered within {grace}s; "
                            if grace > 0
                            else "; "
                        )
                        + "aborting the run"
                    )
                continue
            if role != "worker":
                continue
            r = int(info.get("rank", -1))
            if r not in dead_ranks and r not in done:
                # sweep disabled (recovery_sweep_interval_s == 0): fall
                # back to scheduler-driven recovery over the wire
                declare_dead(r, "dead (missed heartbeats)")
        if time.monotonic() - t_start > cfg.fault.startup_grace_s:
            # a rank that NEVER registered is in neither the dead list
            # (no beats recorded) nor done — without this it would park
            # the monitor forever (e.g. the process crashed on startup)
            registered = {
                int(n["rank"])
                for n in registry.values()
                if n.get("role") == "worker" and "rank" in n
            }
            for r in set(range(num_workers)) - registered - dead_ranks - done:
                declare_dead(r, "never registered (startup failure?)")
        if cfg.fault.straggler_reassign_s > 0:
            ctl.workload_reassign(older_than=cfg.fault.straggler_reassign_s)
        time.sleep(0.5)

    servers = _connect_servers(ctl, worker_rank=-1, num_servers=num_servers, cfg=cfg)
    from parameter_server_tpu.parallel.backend import SocketBackend

    w = SocketBackend(
        servers,
        KeyRange(0, cfg.data.num_keys).even_divide(num_servers),
        cfg.data.num_keys,
        own_handles=False,
    ).weights().ravel()
    out: dict[str, Any] = {
        "merged": ctl.progress_merged(),
        "server_stats": [sh.stats() for sh in servers],
        "nnz_w": int(np.count_nonzero(w)),
        "workloads": ctl.workload_stats(),
        "dead_workers": sorted(dead_ranks),
        # scheduler-process wire/recovery counters; the coordinator runs
        # in-process, so rpc_dedup_hits here covers every control frame
        # the cluster resent or duplicated
        "wire": wire_counters.snapshot(),
        # cluster telemetry merged from every node's heartbeat snapshot
        # (+ this process): counters, per-command latency histograms,
        # named timers — the `cli stats` view, embedded in the run result
        "telemetry": ctl.telemetry()["merged"],
    }
    chaos_stats = coordinator.server.fault_stats()
    if chaos_stats is not None:
        out["chaos"] = chaos_stats
        out["control_frames"] = coordinator.server.frames_in
    if model_out:
        from parameter_server_tpu.utils.checkpoint import dump_weights_text

        dump_weights_text(w, model_out)
        out["model_out"] = model_out
    if cfg.data.val_files:
        from parameter_server_tpu.models.evaluation import evaluate_model

        ev = evaluate_model(
            w, cfg.data.val_files, cfg.data.format, cfg.data.num_keys,
            batch_size=cfg.solver.minibatch,
            max_nnz_per_example=cfg.data.max_nnz_per_example,
        )
        out["val_auc"] = ev["auc"]
        out["val_logloss"] = ev["logloss"]
    for sh in servers:
        sh.shutdown()
        sh.close()
    ctl.close()
    coordinator.stop()
    trace.tracer.flush()  # export this process's spans (no-op if disabled)
    return out


def launch_local(
    app_file: str,
    num_servers: int,
    num_workers: int,
    model_out: str = "",
    timeout: float = 600.0,
    devices: str = "cpu",
    fault_kill: str = "",
    fault_restart_after: float = -1.0,
    ckpt_dir: str = "",
    fault_plan: str = "",
    fault_seed: int = 0,
    trace_dir: str = "",
    trace_sample: int = 1,
    blackbox_dir: str = "",
) -> dict[str, Any]:
    """Spawn scheduler + servers + workers as real processes on this host
    (ref: script/local.sh — the de-facto integration test harness).

    ``devices="cpu"`` (default) pins every spawned node to the CPU backend:
    the harness simulates a multi-host cluster on one machine, and N
    processes must not fight over this host's accelerator (real multi-host
    runs get one process per host from the cluster manager, not from here).
    ``devices="inherit"`` leaves the environment alone.

    ``fault_kill="worker:1@2.0"`` is the fault-injection hook (SURVEY §5.3:
    "fault injection = kill a host process in the simulated integration
    test"): SIGKILL the named node 2.0s after it registers with the
    coordinator, exercising dead-node detection + workload requeue.

    ``fault_restart_after >= 0`` respawns the killed node that many seconds
    after the kill — with ``ckpt_dir`` set (server checkpointing, see
    run_server) this exercises the checkpoint-backed server recovery path.

    ``fault_plan`` (parallel/chaos.py spec) arms a seeded FaultPlan on
    EVERY spawned node's RpcServers via the PS_FAULT_PLAN env var —
    frame-level drop/delay/disconnect/duplicate chaos on top of (or
    instead of) the process-kill fault.

    ``blackbox_dir`` arms the flight recorder + watchdog on every
    spawned node via the PS_BLACKBOX_DIR env var (the PS_TRACE_DIR
    pattern): each process leaves a ``blackbox-<role>-<rank>-<pid>.json``
    dump behind — periodically flushed, so even a SIGKILL'd node's box
    survives for ``cli postmortem`` to merge.
    """
    import os
    import socket as socket_mod
    import subprocess
    import sys

    with socket_mod.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    addr = f"127.0.0.1:{port}"

    child_env = dict(os.environ)
    if devices == "cpu":
        from parameter_server_tpu.utils.hostenv import force_cpu

        force_cpu(child_env)
    if fault_plan:
        FaultPlan.parse(fault_plan, seed=fault_seed)  # fail fast on a typo
        child_env[PLAN_ENV] = fault_plan
        child_env[SEED_ENV] = str(fault_seed)
    if trace_dir:
        # arm tracing on EVERY spawned node (the PS_FAULT_PLAN pattern):
        # each process exports trace-<role>-<rank>-<pid>.json into this dir
        os.makedirs(trace_dir, exist_ok=True)
        child_env[trace.TRACE_DIR_ENV] = trace_dir
        if trace_sample > 1:
            # head sampling rides along: children keep whole traces or
            # drop them, consistently with every other node (the
            # decision is keyed off the trace id, not the process)
            child_env[trace.TRACE_SAMPLE_ENV] = str(int(trace_sample))
    if blackbox_dir:
        # arm the flight recorder on EVERY spawned node (same pattern):
        # any soak failure then leaves a postmortem behind
        os.makedirs(blackbox_dir, exist_ok=True)
        child_env[flightrec.BLACKBOX_DIR_ENV] = blackbox_dir
    _export_witness_env(child_env)

    import tempfile

    logdir = tempfile.mkdtemp(prefix="pslaunch_")

    def spawn(role: str, rank: int, attempt: int = 0) -> subprocess.Popen:
        cmd = [
            sys.executable, "-m", "parameter_server_tpu.cli", "node",
            "--role", role, "--rank", str(rank), "--scheduler", addr,
            "--num_servers", str(num_servers), "--num_workers", str(num_workers),
            "--app_file", app_file,
        ]
        if role == "scheduler" and model_out:
            cmd += ["--model_out", model_out]
        if role == "server" and ckpt_dir:
            cmd += ["--ckpt_dir", ckpt_dir]
        # child output goes to files, not PIPEs: nobody drains N pipes while
        # training runs, and a chatty child must never block on a full pipe
        tag = f"{role}-{rank}" + (f"-r{attempt}" if attempt else "")
        out_f = open(f"{logdir}/{tag}.out", "w+")
        err_f = open(f"{logdir}/{tag}.err", "w+")
        p = subprocess.Popen(cmd, stdout=out_f, stderr=err_f, text=True, env=child_env)
        p._ps_logs = (out_f, err_f)  # type: ignore[attr-defined]
        p._ps_tag = f"{role}:{rank}"  # type: ignore[attr-defined]
        return p

    def logs_of(p: subprocess.Popen) -> tuple[str, str]:
        out_f, err_f = p._ps_logs  # type: ignore[attr-defined]
        out_f.seek(0)
        err_f.seek(0)
        return out_f.read(), err_f.read()

    procs = [spawn("scheduler", 0)]
    procs += [spawn("server", r) for r in range(num_servers)]
    procs += [spawn("worker", r) for r in range(num_workers)]
    victims: list[subprocess.Popen] = []  # processes whose death is the test
    replacement_box: list[subprocess.Popen] = []  # assassin -> main handoff
    respawn_lock = threading.Lock()
    harness_done = threading.Event()
    if fault_kill:
        role_rank, delay_s = fault_kill.split("@")
        kill_role, kill_rank = role_rank.split(":")
        killed_tag = f"{kill_role}:{int(kill_rank)}"
        victim = next(p for p in procs if p._ps_tag == killed_tag)  # type: ignore[attr-defined]
        victims.append(victim)

        def assassin() -> None:
            # wait for the victim to REGISTER first: killing a process that
            # never reached the coordinator would leave the scheduler unable
            # to tell "dead" from "still starting up"
            ctl = ControlClient(addr, retries=600)
            try:
                while True:
                    if any(
                        n.get("role") == kill_role
                        and int(n.get("rank", -1)) == int(kill_rank)
                        for n in ctl.nodes().values()
                    ):
                        break
                    time.sleep(0.2)
            finally:
                ctl.close()
            time.sleep(float(delay_s))
            victim.kill()
            if fault_restart_after >= 0:
                time.sleep(fault_restart_after)
                # checkpoint-backed recovery: the replacement re-registers
                # under the same rank and reloads its range dump. Spawned
                # into its own box (NOT procs — the main wait loop is
                # iterating that) and only while the scheduler is alive:
                # respawning after the run ended would leave a server
                # nobody ever shuts down.
                with respawn_lock:
                    if not harness_done.is_set() and procs[0].poll() is None:
                        replacement_box.append(
                            spawn(kill_role, int(kill_rank), attempt=1)
                        )

        threading.Thread(target=assassin, daemon=True).start()
    deadline = time.monotonic() + timeout
    timed_out = False
    try:
        for p in procs:
            try:
                p.wait(timeout=max(deadline - time.monotonic(), 1))
            except subprocess.TimeoutExpired:
                timed_out = True
                break
        # the replacement (if any) exits when the scheduler shuts it down;
        # a replacement spawned too close to run end may have nobody left
        # to do that — reap it leniently rather than hang or fail the run
        with respawn_lock:
            harness_done.set()  # no further respawns
        for p in replacement_box:
            if not timed_out:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    pass
    finally:
        with respawn_lock:
            harness_done.set()
        for p in replacement_box:
            victims.append(p)  # its rc never decides the run's outcome
            procs.append(p)
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    outs = [(p, *logs_of(p)) for p in procs]
    for p, _, _ in outs:
        p._ps_logs[0].close()  # type: ignore[attr-defined]
        p._ps_logs[1].close()  # type: ignore[attr-defined]
    if timed_out:
        tails = "\n".join(
            f"--- {p._ps_tag} rc={p.returncode} ---\n{err[-1500:]}"  # type: ignore[attr-defined]
            for p, _, err in outs
        )
        raise RuntimeError(f"multi-process run timed out after {timeout}s:\n{tails}")
    for p, stdout, stderr in outs:
        if p.returncode != 0 and not any(p is v for v in victims):
            raise RuntimeError(
                f"node {p._ps_tag} failed rc={p.returncode}:\n{stderr[-2000:]}"  # type: ignore[attr-defined]
            )
    # scheduler prints the result JSON on its last stdout line
    return json.loads(outs[0][1].strip().splitlines()[-1])


def run_node(
    cfg: PSConfig,
    role: str,
    rank: int,
    scheduler: str,
    num_servers: int,
    num_workers: int,
    model_out: str = "",
    bind_host: str = "127.0.0.1",
    advertise_host: str = "",
    ckpt_dir: str = "",
) -> dict[str, Any] | None:
    """Role dispatch for one spawned process (ref: App::Create + main.cc)."""
    import os

    # the ONE unknown-role gate, before ANY arming side effects (an
    # armed tracer/recorder/profiler named after a typo'd role, or a
    # KeyError out of the metrics-port table, are worse diagnostics);
    # the table doubles as the metrics-endpoint port layout below
    metrics_offset = {
        "scheduler": 0,
        "server": 1 + rank,
        "worker": 1 + num_servers + rank,
    }.get(role)
    if metrics_offset is None:
        raise ValueError(f"unknown role {role!r}")

    # arm tracing for this node: config [trace] trace_dir wins, then the
    # inherited PS_TRACE_DIR env (launch_local's arming path); the process
    # name makes each node's export file self-describing
    tdir = cfg.trace.trace_dir or os.environ.get(trace.TRACE_DIR_ENV, "")
    if tdir:
        # head-sampling rate: an explicit [trace] sample wins, else the
        # inherited PS_TRACE_SAMPLE (launch_local's arming path)
        sample = cfg.trace.sample
        if sample <= 1:
            sample = trace._env_sample()
        trace.configure(
            tdir, capacity=cfg.trace.capacity,
            process_name=f"{role}-{rank}",
            sample=sample,
            # tail-biased capture (ISSUE 15): on by default — promotion
            # rescues the slow traces head sampling would drop
            tail=cfg.trace.tail,
            tail_k=cfg.trace.tail_k,
            tail_limbo=cfg.trace.tail_limbo,
        )
    # arm the black box: config [blackbox] dir wins, then the inherited
    # PS_BLACKBOX_DIR (launch_local's arming path) — re-configured even
    # when env-armed at import so the dump carries a role-rank name
    bdir = cfg.blackbox.dir or os.environ.get(flightrec.BLACKBOX_DIR_ENV, "")
    if bdir:
        flightrec.configure(
            bdir, capacity=cfg.blackbox.capacity,
            process_name=f"{role}-{rank}",
            flush_interval_s=cfg.blackbox.flush_interval_s,
            watchdog_interval_s=cfg.blackbox.watchdog_interval_s,
            stall_timeout_s=cfg.blackbox.stall_timeout_s,
        )
    # arm the continuous profiler: config [profile] hz wins, then the
    # inherited PS_PROFILE (env-armed at import; re-configured here so
    # the dump carries a role-rank name) — ISSUE 13
    from parameter_server_tpu.utils import profiler, timeseries

    prof_hz = cfg.profile.hz if cfg.profile.hz > 0 else profiler.env_hz()
    if prof_hz > 0:
        profiler.configure(
            prof_hz, top_n=cfg.profile.top_n,
            max_depth=cfg.profile.max_depth,
            dump_dir=cfg.profile.dump_dir
            or os.environ.get(profiler.PROFILE_DIR_ENV, ""),
            process_name=f"{role}-{rank}",
        )
    # OpenMetrics scrape endpoint: [timeseries] metrics_port (or the
    # inherited PS_METRICS_PORT) is the BASE port; each role-rank binds
    # a deterministic offset so one host's processes never collide
    mbase = cfg.timeseries.metrics_port or int(
        os.environ.get(timeseries.METRICS_PORT_ENV, "0") or 0
    )
    # size this node's local delta ring (fed by each beat's
    # beat_telemetry roll; served windowed by /healthz)
    timeseries.reset_local_ring(cfg.timeseries.capacity)
    msrv = roller = None
    if mbase > 0:
        msrv = timeseries.start_metrics_server(
            mbase + metrics_offset, process_name=f"{role}-{rank}",
            host=cfg.timeseries.metrics_host,
            window_s=cfg.timeseries.window_s,
        )
        if role == "scheduler":
            # servers/workers roll the local ring on every beat; the
            # scheduler never beats, so without this its /healthz
            # window would stay empty forever and read as a wedged node
            roller = timeseries.Roller(cfg.fault.heartbeat_interval_s)
    # audit plane (ISSUE 14): the scheduler has no heartbeat reporter,
    # so its own spool (SSP clock movements, control rpc.reply acks) is
    # drained inline by the coordinator's audit pass — arm it here, with
    # the same role gate the _Beats path applies on servers/workers
    armed_spool = False
    if role == "scheduler" and cfg.audit.enabled:
        flightrec.configure_spool(
            cfg.audit.spool_capacity, cfg.audit.batch_events
        )
        armed_spool = True
    try:
        if role == "scheduler":
            host, port = scheduler.rsplit(":", 1)
            coord = Coordinator(
                host, int(port),
                heartbeat_timeout_s=cfg.fault.heartbeat_timeout_s,
                fault_plan=_plan_from_cfg(cfg),
                slo_cfg=cfg.slo,
                series_capacity=cfg.timeseries.capacity,
                series_window_s=cfg.timeseries.window_s,
                audit_cfg=cfg.audit,
            )
            return run_scheduler(cfg, coord, num_servers, num_workers, model_out)
        if role == "server":
            run_server(
                cfg, scheduler, rank, num_servers,
                bind_host=bind_host, advertise_host=advertise_host,
                ckpt_dir=ckpt_dir,
            )
            return None
        run_worker(cfg, scheduler, rank, num_servers)
        return None
    finally:
        if roller is not None:
            roller.close()
        if msrv is not None:
            msrv.close()
        if armed_spool:
            flightrec.configure_spool(None)
