"""Static collective-traffic accounting.

Reference analog: Postoffice counts bytes sent/received per filter stage
and the scheduler reports traffic savings. On a pod, per-step collective
sizes are statically computable from the program — this module is that
accounting, used by progress reports and perf work."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class StepTraffic:
    """Estimated bytes moved by ONE SPMD train step (per device)."""

    pull_bytes: int  # psum over kv of pulled rows
    push_bytes: int  # all_gather of (idx, grads) over data
    total_bytes: int


def linear_step_traffic(
    unique_capacity: int,
    vdim: int,
    data_shards: int,
    kv_shards: int,
    value_bytes: int = 4,
    index_bytes: int = 4,
    push_mode: str = "per_worker",
    num_keys: int = 0,
) -> StepTraffic:
    """Traffic of the sparse-LR SPMD step (parallel.spmd).

    pull: psum over 'kv' of a (U, vdim) float array — ring all-reduce moves
    ~2 * (S-1)/S of the array per device.
    push, per_worker mode: all_gather over 'data' of (U,) indices +
    (U, vdim) grads — ring gather moves (D-1)/D of the full gathered size
    per device.
    push, aggregate mode: psum over 'data' of the dense
    (num_keys/kv_shards, vdim) range slice (+ the touched-count column) —
    ~2 * (D-1)/D of the slice per device, independent of D·U. Crossover:
    aggregate wins when 2·(S+...)·slice < D·U rows, i.e. for dense-enough
    batches or large worker counts."""
    u = unique_capacity
    pull = 0
    if kv_shards > 1:
        pull = int(2 * (kv_shards - 1) / kv_shards * u * vdim * value_bytes)
    push = 0
    if data_shards > 1:
        if push_mode == "aggregate":
            if num_keys <= 0:
                raise ValueError("aggregate mode needs num_keys")
            slice_rows = num_keys // kv_shards
            full = slice_rows * (vdim + 1) * value_bytes  # grads + touched col
            push = int(2 * (data_shards - 1) / data_shards * full)
        elif push_mode == "quantized":
            # int8 payload + one f32 scale per worker (fixing_float as a
            # quantized collective); indices unchanged
            full = data_shards * (u * (index_bytes + vdim) + value_bytes)
            push = int((data_shards - 1) / data_shards * full)
        else:
            full = data_shards * u * (index_bytes + vdim * value_bytes)
            push = int((data_shards - 1) / data_shards * full)
    return StepTraffic(pull, push, pull + push)


@dataclass(frozen=True)
class WireTraffic:
    """Estimated bytes for ONE pull+push round against one shard server
    over the TCP wire tier (payloads only; each of the 4 frames adds
    ~8 B length prefix + a small JSON header on top)."""

    out_bytes: int  # worker -> server: pull request + push request
    in_bytes: int  # server -> worker: pull reply (+ push ack header)


def wire_step_traffic(
    num_unique: int,
    vdim: int = 1,
    key_bytes: int = 4,
    value_bytes: int = 4,
    send_keys: bool = True,
) -> WireTraffic:
    """Payload traffic of one wire-tier worker step (multislice tier):
    the batch's key list rides the wire ONCE per step — the pull sends it
    and primes the key-caching signature, so the same step's push is
    sig-only; the pull reply carries U weights and the push carries U
    gradients. send_keys=False models a fully warm cache (repeated key
    set): both calls are sig-only. Reconciled against the MEASURED
    RpcClient byte counters in tests/test_multislice.py — the reference's
    Postoffice counters report exactly this quantity per filter stage."""
    u = num_unique
    keys = u * key_bytes if send_keys else 0
    return WireTraffic(
        out_bytes=keys + u * vdim * value_bytes,
        in_bytes=u * vdim * value_bytes,
    )


def quantization_savings(num_bytes: int, value_bytes: int = 4) -> float:
    """Fraction of push payload saved by the fixed-point codec on DCN
    (ref: the filter savings report)."""
    return 1.0 - num_bytes / value_bytes
