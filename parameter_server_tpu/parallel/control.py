"""Host-side control plane over TCP: the cross-process tier.

Reference analog: src/system/van.* + postoffice.* — ZeroMQ sockets carrying
protobuf ``Task`` headers plus raw ``SArray`` payloads, dispatched to
Customers; the scheduler holds the node registry, barriers, heartbeats and
merged progress.

On a TPU pod the *data plane* is XLA collectives (parallel/spmd.py) and this
layer is deliberately NOT on it. What genuinely remains host-side —
scheduler traffic (node registry, barriers, the SSP clock, the workload
pool, progress merging, heartbeats, small blob exchange) — rides this tiny
TCP layer, exactly the role SURVEY.md §5.8 assigns to "jax.distributed's KV
store / a tiny host TCP layer". It is also the transport the cross-slice
(DCN) push/pull tier builds on (parallel/multislice.py), where the
reference's message filters become meaningful again.

Wire format (ref: Message = Task proto header + SArray payloads):

    u32 header_len | u32 payload_len | header JSON | payload bytes

The header carries the command and scalar fields; ``arrays`` in the header
describes the (name, dtype, shape) of each contiguous numpy payload. With
``zip`` set the payload block is zlib-compressed (ref: the compressing
filter, src/filter/compressing.h — byte compression earns its place back on
a real wire).

Delivery semantics (ref: the paper's vector-clock idempotent
retransmission, rebuilt for this wire format): every ``RpcClient`` request
carries a client id + sequence number; on a mid-call socket error or
truncated frame the client transparently reconnects (exponential backoff +
jitter) and *resends the same sequence number*. The server keeps a small
per-client reply cache, so a resent or duplicated non-idempotent command
(``workload_fetch``, ``ssp_finish``, ``barrier`` arrivals, pushes) is
answered from the cache instead of double-applied — at-least-once delivery
on the wire, exactly-once application at the handler.
"""

from __future__ import annotations

import json
import random
import socket
import struct
import threading
import time
import uuid
import zlib
from collections import OrderedDict
from typing import Any, Callable

import numpy as np

from parameter_server_tpu.parallel.chaos import FaultPlan
from parameter_server_tpu.parallel.ssp import SSPClock
from parameter_server_tpu.parallel.workload import WorkloadPool
from parameter_server_tpu.utils import trace
from parameter_server_tpu.utils.heartbeat import HeartbeatMonitor
from parameter_server_tpu.utils.metrics import (
    latency_histograms,
    merge_progress,
    merge_telemetry,
    telemetry_snapshot,
    wire_counters,
)

_LEN = struct.Struct("<II")

Arrays = dict[str, np.ndarray]


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        k = sock.recv_into(view[got:], n - got)
        if k == 0:
            raise ConnectionError("peer closed")
        got += k
    return bytes(buf)


def send_frame(
    sock: socket.socket, header: dict[str, Any], arrays: Arrays | None = None
) -> int:
    """Send one framed message; returns bytes put on the wire (ref: the
    Postoffice per-message byte counters)."""
    arrays = arrays or {}
    metas = []
    chunks = []
    for name, a in arrays.items():
        a = np.ascontiguousarray(a)
        metas.append([name, a.dtype.str, list(a.shape)])
        chunks.append(a.tobytes())
    payload = b"".join(chunks)
    if header.get("zip"):
        payload = zlib.compress(payload, level=1)
    h = dict(header)
    h["arrays"] = metas
    hb = json.dumps(h).encode()
    frame = _LEN.pack(len(hb), len(payload)) + hb + payload
    sock.sendall(frame)
    # frame-layer byte accounting: EVERY framed message — coordinator and
    # control traffic included — lands in the process-global counters, so
    # the cluster's wire-byte columns no longer undercount to just the
    # ServerHandle data plane
    wire_counters.inc("wire_bytes_out", len(frame))
    return len(frame)


def recv_frame_sized(
    sock: socket.socket,
) -> tuple[dict[str, Any], Arrays, int]:
    """recv_frame plus the frame's wire size (for traffic counters)."""
    hlen, plen = _LEN.unpack(_recv_exact(sock, _LEN.size))
    header = json.loads(_recv_exact(sock, hlen))
    payload = _recv_exact(sock, plen) if plen else b""
    nbytes = _LEN.size + hlen + plen
    wire_counters.inc("wire_bytes_in", nbytes)  # frame layer (see send_frame)
    if header.get("zip"):
        payload = zlib.decompress(payload)
    arrays: Arrays = {}
    off = 0
    for name, dtype, shape in header.pop("arrays", []):
        dt = np.dtype(dtype)
        n = int(np.prod(shape)) if shape else 1
        nb = n * dt.itemsize
        arrays[name] = np.frombuffer(
            payload, dtype=dt, count=n, offset=off
        ).reshape(shape)
        off += nb
    return header, arrays, nbytes


def recv_frame(sock: socket.socket) -> tuple[dict[str, Any], Arrays]:
    header, arrays, _ = recv_frame_sized(sock)
    return header, arrays


class _DedupEntry:
    """One cached reply. ``event`` lets a resent/duplicated frame that
    arrives while the first delivery is still being applied (e.g. parked in
    a barrier) wait for THAT application's reply instead of re-applying."""

    __slots__ = ("event", "rep", "arrays")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.rep: dict[str, Any] | None = None
        self.arrays: Arrays | None = None


# Reply-cache bounds: clients serialize requests, so at most one entry per
# client is ever truly live; small slack absorbs pathological interleavings.
_DEDUP_PER_CLIENT = 4
_DEDUP_CLIENTS = 1024


class RpcServer:
    """Thread-per-connection TCP server dispatching framed requests to a
    handler (shared by the Coordinator and the shard servers). The handler
    may raise ``Shutdown`` to stop the server after replying.

    Requests carrying a client id + sequence number are deduplicated
    through a per-client reply cache (see module docstring). A
    :class:`~parameter_server_tpu.parallel.chaos.FaultPlan` may be armed —
    explicitly or via the ``PS_FAULT_PLAN`` env var — to perturb received
    frames for recovery testing."""

    class Shutdown(Exception):
        pass

    def __init__(
        self,
        handler: Callable[[dict[str, Any], Arrays], tuple[dict[str, Any], Arrays]],
        host: str = "127.0.0.1",
        port: int = 0,
        fault_plan: FaultPlan | None = None,
        idempotent_cmds: frozenset[str] = frozenset(),
        expose_identity: bool = False,
    ):
        self._handler = handler
        # re-applying these is harmless, so resends bypass the reply cache
        # entirely — caching their (potentially large: pull/dump/kv_get
        # payloads) replies would pin the arrays of the last
        # _DEDUP_PER_CLIENT requests per client for no correctness gain
        self._idempotent_cmds = idempotent_cmds
        # hand the deduped (cid, seq) identity to the handler (as _cid/_seq
        # header fields) so it can keep its own durable dedup ledger — the
        # shard server persists applied push seqs into its checkpoint
        self._expose_identity = expose_identity
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.address = f"{host}:{self._sock.getsockname()[1]}"
        self._stop = threading.Event()
        self.bytes_in = 0
        self.bytes_out = 0
        self.frames_in = 0
        self._counter_lock = threading.Lock()  # counters shared by conn threads
        self._accept_thread: threading.Thread | None = None
        self._conns: set[socket.socket] = set()  # live, for stop() to sever
        # cid -> (seq -> _DedupEntry), both LRU-bounded
        self._dedup: OrderedDict[str, OrderedDict[int, _DedupEntry]] = OrderedDict()
        self._dedup_lock = threading.Lock()
        self.fault_plan = fault_plan if fault_plan is not None else FaultPlan.from_env()

    def start(self) -> "RpcServer":
        self._accept_thread = threading.Thread(target=self._accept, daemon=True)
        self._accept_thread.start()
        return self

    def _accept(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # socket closed by stop()
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        with self._counter_lock:
            self._conns.add(conn)
        # register-then-check pairs with stop()'s set-then-sever: a conn
        # accepted concurrently with stop() is either seen by the sweep
        # above or bails here — it can never serve a stopped server
        if self._stop.is_set():
            with self._counter_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass
            return
        try:
            while True:
                header, arrays, nbytes = recv_frame_sized(conn)
                with self._counter_lock:
                    self.bytes_in += nbytes
                    self.frames_in += 1
                fault = (
                    self.fault_plan.decide(header.get("cmd", ""))
                    if self.fault_plan is not None
                    else None
                )
                if fault is not None and fault.action == "drop":
                    return  # request lost before it applied; conn closed below
                if fault is not None and fault.action == "delay":
                    time.sleep(fault.delay_s)
                cid = header.pop("_cid", None)
                seq = header.pop("_seq", None)
                tctx = header.pop("_trace", None)  # caller's span identity
                cmd_name = header.get("cmd", "?")
                # copy BEFORE dispatch: handlers mutate the header (pop cmd)
                dup_header = (
                    dict(header)
                    if fault is not None and fault.action == "duplicate"
                    else None
                )
                t_svc = time.perf_counter()
                try:
                    # activate() binds the wire-borne trace context so the
                    # dispatch span (and any handler spans under it) joins
                    # the client's trace — one logical push is one trace id
                    # across processes
                    with trace.activate(tctx), trace.span(
                        f"rpc.serve.{cmd_name}", cat="rpc", bytes_in=nbytes
                    ):
                        rep, rep_arrays = self._dispatch(
                            cid, seq, header, arrays
                        )
                        if dup_header is not None:
                            # the same frame delivered twice: without dedup
                            # this double-applies (copy's reply discarded)
                            self._dispatch(cid, seq, dup_header, arrays)
                    latency_histograms.observe(
                        f"server.{cmd_name}", time.perf_counter() - t_svc
                    )
                except RpcServer.Shutdown:
                    try:
                        send_frame(conn, {"ok": True})
                    finally:
                        # stop() even when the ack send fails: the reply
                        # cache would answer a resent shutdown without
                        # re-running the handler, so nothing would ever
                        # stop the server (shutdown is the one command
                        # whose side effect happens after the reply)
                        self.stop()
                    return
                if fault is not None and fault.action == "disconnect":
                    return  # applied, but the reply is lost; conn closed below
                sent = send_frame(conn, rep, rep_arrays)
                with self._counter_lock:
                    self.bytes_out += sent
        except (ConnectionError, OSError):
            return  # client went away; its requests died with it
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._counter_lock:
                self._conns.discard(conn)

    def _dispatch(
        self, cid: str | None, seq: int | None, header: dict[str, Any], arrays: Arrays
    ) -> tuple[dict[str, Any], Arrays]:
        """Apply-or-replay: the first delivery of (cid, seq) runs the
        handler and caches its reply; every later delivery returns that
        cached reply (waiting for it if the first is still in flight)."""
        if cid is None or seq is None:  # legacy/raw frame: no dedup contract
            return self._apply(header, arrays)
        if header.get("cmd") in self._idempotent_cmds:
            return self._apply(header, arrays)  # re-apply beats caching
        if self._expose_identity:
            header["_cid"], header["_seq"] = cid, seq
        with self._dedup_lock:
            per = self._dedup.get(cid)
            if per is None:
                per = self._dedup[cid] = OrderedDict()
                while len(self._dedup) > _DEDUP_CLIENTS:
                    self._dedup.popitem(last=False)
            else:
                self._dedup.move_to_end(cid)
            ent = per.get(seq)
            owner = ent is None
            if owner:
                ent = per[seq] = _DedupEntry()
                while len(per) > _DEDUP_PER_CLIENT:
                    per.popitem(last=False)
        if not owner:
            ent.event.wait()  # may park on a blocking command's first apply
            wire_counters.inc("rpc_dedup_hits")
            return ent.rep, ent.arrays  # type: ignore[return-value]
        try:
            rep, rep_arrays = self._apply(header, arrays)
        except RpcServer.Shutdown:
            # cache the ack a resend would expect, then let _serve stop us
            ent.rep, ent.arrays = {"ok": True}, {}
            ent.event.set()
            raise
        if rep.get("_transient"):
            # did-not-commit reply (e.g. the shard server's need_keys
            # bounce): nothing was applied, so a later delivery of this
            # SAME (cid, seq) must re-run the handler, not replay this
            # bounce — drop the entry instead of caching it. This is what
            # lets one logical mutation keep one dedup identity across
            # the key-caching protocol's two-phase exchange.
            with self._dedup_lock:
                per = self._dedup.get(cid)
                if per is not None and per.get(seq) is ent:
                    del per[seq]
        ent.rep, ent.arrays = rep, rep_arrays
        ent.event.set()
        return rep, rep_arrays

    def _apply(
        self, header: dict[str, Any], arrays: Arrays
    ) -> tuple[dict[str, Any], Arrays]:
        try:
            return self._handler(header, arrays)
        except RpcServer.Shutdown:
            raise
        except Exception as e:  # surface handler errors to the caller
            return {"ok": False, "error": repr(e)}, {}

    def fault_stats(self) -> dict[str, int] | None:
        """Armed plan's fire counts (None when no plan is armed)."""
        return None if self.fault_plan is None else self.fault_plan.stats()

    def stop(self) -> None:
        self._stop.set()
        # shutdown BEFORE close: the accept thread parked in accept() holds
        # the open file description, so a bare close() leaves the kernel
        # socket listening forever — the port could never be rebound by a
        # restarted server and stop() would not actually stop accepting
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        # sever live connections: a stopped server must look DEAD to its
        # clients (their self-healing reconnect logic owns what happens
        # next), not leave them parked on a half-alive socket
        with self._counter_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass


class RpcClient:
    """One persistent connection; requests are serialized under a lock
    (the reference's per-remote-node send queue discipline).

    Self-healing: every request carries this client's id and a sequence
    number. A mid-call ``OSError``/truncated frame triggers transparent
    reconnect (exponential backoff + jitter, bounded by
    ``reconnect_timeout_s``) and a resend of the SAME sequence number — the
    server's reply cache makes the retry exactly-once even for
    non-idempotent commands. The window only bounds time spent *retrying
    after a failure*; a healthy blocking call (barrier, ssp_wait) may park
    indefinitely as before."""

    def __init__(
        self,
        address: str,
        retries: int = 50,
        retry_delay: float = 0.1,
        reconnect_timeout_s: float = 30.0,
        cid: str | None = None,
        start_seq: int = 0,
    ):
        """``cid``/``start_seq`` transfer a logical client identity into a
        rebuilt connection (ServerHandle recovery): the server's dedup
        state is keyed by cid, so a resend after the rebuild is only
        recognized if the identity survives. ``start_seq`` must clear the
        old client's counter or fresh requests would collide with (and be
        swallowed by) cached replies of old sequence numbers."""
        self._address = address
        self._cid = cid or uuid.uuid4().hex[:16]
        self._next_seq = start_seq
        self._reconnect_timeout_s = reconnect_timeout_s
        self._rng = random.Random()  # backoff jitter: no determinism contract
        self._lock = threading.Lock()
        self._closed = False
        self.bytes_out = 0
        self.bytes_in = 0
        last: Exception | None = None
        for _ in range(retries):
            try:
                self._sock: socket.socket | None = self._connect()
                break
            except OSError as e:  # server may still be binding
                last = e
                time.sleep(retry_delay)
        else:
            raise ConnectionError(f"cannot reach {address}: {last}")

    def _connect(self) -> socket.socket:
        host, port = self._address.rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=30)
        # blocking calls (barrier, ssp_wait) may legitimately park for longer
        # than any fixed socket timeout; request-level timeouts are carried in
        # the header and enforced server-side, the launcher is the backstop
        s.settimeout(None)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    def call(
        self, cmd: str, arrays: Arrays | None = None, *, _retry: bool = True,
        _seq: int | str | None = None, **fields: Any,
    ) -> tuple[dict[str, Any], Arrays]:
        """``_seq`` overrides the auto-allocated sequence number: a caller
        that re-issues a logical request across *rebuilt* clients (e.g.
        ``ServerHandle._keyed_call``) passes the same value each time so
        every delivery is one dedup identity. Caller-owned seqs must live
        in a disjoint namespace (the handle uses ``"k<n>"`` strings) so
        they can never collide with the internal integer counter."""
        with self._lock:
            if _seq is None:
                _seq = self._next_seq
                self._next_seq += 1
            header = {"cmd": cmd, "_cid": self._cid, "_seq": _seq, **fields}
            t0 = time.perf_counter()
            with trace.span(f"rpc.{cmd}", cat="rpc", addr=self._address):
                # propagate this span's identity in the header so the
                # server's dispatch span joins the same trace
                ctx = trace.wire_context()
                if ctx is not None:
                    header["_trace"] = ctx
                rep, rep_arrays = self._call_locked(header, arrays, _retry)
            # client-observed latency: queueing + wire + service + any
            # transparent retries/reconnects this call absorbed
            latency_histograms.observe(
                f"client.{cmd}", time.perf_counter() - t0
            )
        if not rep.get("ok", True):
            raise RuntimeError(f"{cmd} failed remotely: {rep.get('error')}")
        return rep, rep_arrays

    def _call_locked(
        self, header: dict[str, Any], arrays: Arrays | None, retry: bool
    ) -> tuple[dict[str, Any], Arrays]:
        attempt = 0
        deadline = time.monotonic() + self._reconnect_timeout_s
        while True:
            try:
                if self._closed:
                    raise ConnectionError(f"client to {self._address} is closed")
                if self._sock is None:
                    self._sock = self._connect()
                    wire_counters.inc("rpc_reconnects")
                    trace.instant(
                        "rpc.reconnect", cat="rpc", addr=self._address
                    )
                self.bytes_out += send_frame(self._sock, header, arrays)
                rep, rep_arrays, nbytes = recv_frame_sized(self._sock)
                self.bytes_in += nbytes
                return rep, rep_arrays
            except (ConnectionError, OSError):
                self._drop_sock()
                if self._closed or not retry or time.monotonic() >= deadline:
                    raise
                wire_counters.inc("rpc_retries")
                trace.instant(
                    "rpc.retry", cat="rpc", addr=self._address,
                    attempt=attempt,
                )
                # exponential backoff + jitter: a server resetting every
                # connect must not be hammered at full speed, and lockstep
                # clients must not reconnect in synchronized waves
                delay = min(0.05 * (1 << min(attempt, 6)), 2.0)
                delay *= 0.5 + self._rng.random()
                time.sleep(min(delay, max(deadline - time.monotonic(), 0.0)))
                attempt += 1

    @property
    def identity(self) -> tuple[str, int]:
        """(cid, next unused internal seq) — transfer into a replacement
        client (``RpcClient(..., cid=, start_seq=)``) so the server's
        dedup state keeps recognizing the logical caller across rebuilds."""
        return self._cid, self._next_seq

    def _drop_sock(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        self._closed = True  # no reconnects on behalf of a closed client
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass


class Coordinator:
    """The scheduler endpoint (ref: Postoffice on the scheduler node).

    Owns: node registry, named barriers, a blob KV (small host arrays),
    the workload pool, merged progress, heartbeats, and the SSP clock.
    All commands are served by ``RpcServer`` threads; blocking commands
    (barrier / blocking kv_get / ssp_wait) park the connection's thread.

    Self-healing control plane: ``start_recovery`` runs a sweep thread that
    promotes ``HeartbeatMonitor.dead()`` into ``WorkloadPool.
    reassign_worker`` + SSP-clock release, so a dead worker's tasks drain
    onto survivors without any scheduler-side polling logic (ref: the
    scheduler's dead-node handling driving recovery).
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        heartbeat_timeout_s: float = 30.0,
        recovery_interval_s: float = 0.0,
        fault_plan: FaultPlan | None = None,
    ):
        self._nodes: dict[int, dict[str, Any]] = {}
        self._next_id = 0
        self._barriers: dict[str, list[int]] = {}  # name -> [arrived, generation]
        self._kv: dict[str, tuple[dict, Arrays]] = {}
        self._pool: WorkloadPool | None = None
        self._progress: dict[int, dict[str, Any]] = {}
        self._monitor = HeartbeatMonitor(heartbeat_timeout_s)
        self._clock: SSPClock | None = None
        self._cv = threading.Condition()
        self._recovered: dict[int, dict[str, Any]] = {}  # worker rank -> info
        self._sweep_stop = threading.Event()
        self._sweep_thread: threading.Thread | None = None
        self.server = RpcServer(
            self._handle, host, port, fault_plan=fault_plan,
            # reads and last-writer-wins/monotonic writes: re-applying a
            # resend is harmless, and kv_get replies can carry model-sized
            # blobs that must not be pinned in the reply cache
            idempotent_cmds=frozenset({
                "kv_get", "kv_set", "nodes", "beat", "progress",
                "progress_merged", "workload_stats", "ssp_progress",
                "telemetry",
            }),
        )
        self.server.start()
        self.address = self.server.address
        if recovery_interval_s > 0:
            self.start_recovery(recovery_interval_s)

    # -- recovery sweep --------------------------------------------------

    def start_recovery(self, interval_s: float = 0.5) -> None:
        """Arm the dead-node sweep (idempotent): every ``interval_s`` the
        monitor's overdue workers have their workloads requeued and their
        SSP clock retired, so surviving workers drain their tasks."""
        if self._sweep_thread is not None:
            return
        def sweep() -> None:
            while not self._sweep_stop.wait(interval_s):
                self._sweep_once()
        self._sweep_thread = threading.Thread(target=sweep, daemon=True)
        self._sweep_thread.start()

    def _sweep_once(self) -> None:
        for nid in self._monitor.dead():
            with self._cv:
                info = dict(self._nodes.get(nid, {}))
            if info.get("role") != "worker" or "rank" not in info:
                continue  # dead servers are the scheduler's call (grace /
                # checkpoint-restart policy lives there, not here)
            rank = int(info["rank"])
            with self._cv:
                finished = f"worker_done/{rank}" in self._kv
            if finished:
                # clean completion: drop the corpse so dead() stays the
                # actionable list
                self._monitor.forget(nid)
                continue
            # no handled-before guard: forget(nid) below keeps a handled
            # death out of dead(), and a forgotten node only reappears
            # through a fresh beat — i.e. it was ALIVE again (restarted
            # rank or falsely-declared-dead straggler) and may hold fresh
            # workloads, so its next death must be recovered again too.
            # A second recovery of a rank overwrites its report entry.
            requeued = self._pool.reassign_worker(rank) if self._pool else []
            if self._clock is not None:
                self._clock.retire(rank)
            with self._cv:
                self._recovered[rank] = {"node_id": nid, "requeued": requeued}
                self._cv.notify_all()
            self._monitor.forget(nid)
            wire_counters.inc("workers_recovered")

    # -- dispatch --------------------------------------------------------

    def _handle(
        self, header: dict[str, Any], arrays: Arrays
    ) -> tuple[dict[str, Any], Arrays]:
        cmd = header.pop("cmd")
        fn = getattr(self, f"_cmd_{cmd}", None)
        if fn is None:
            raise ValueError(f"unknown control command {cmd!r}")
        return fn(header, arrays)

    def _cmd_register(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        with self._cv:
            node_id = self._next_id
            self._next_id += 1
            self._nodes[node_id] = {"role": h.get("role", "?"), **h}
            self._cv.notify_all()
        return {"ok": True, "node_id": node_id}, {}

    def _cmd_nodes(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        with self._cv:
            # copy: serialization happens after the lock is released, and a
            # concurrent register mutating the live dict mid-dumps would
            # kill the connection thread
            return {"ok": True, "nodes": dict(self._nodes)}, {}

    def _cmd_barrier(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        """Block until ``count`` callers reach barrier ``name`` (ref:
        Postoffice::Barrier over node groups)."""
        name, count = h["name"], int(h["count"])
        with self._cv:
            st = self._barriers.setdefault(name, [0, 0])
            st[0] += 1
            if st[0] >= count:
                st[0] = 0
                st[1] += 1
                self._cv.notify_all()
                return {"ok": True}, {}
            gen = st[1]
            ok = self._cv.wait_for(
                lambda: self._barriers[name][1] > gen, timeout=h.get("timeout")
            )
            if not ok and self._barriers[name][1] == gen:
                st[0] -= 1  # withdraw our arrival: a later generation must
                # not release early on a participant that already gave up
        return {"ok": ok, "error": "barrier timeout" if not ok else None}, {}

    def _cmd_kv_set(self, h: dict, arrays: Arrays) -> tuple[dict, Arrays]:
        with self._cv:
            self._kv[h["key"]] = ({"fields": h.get("fields", {})}, arrays)
            self._cv.notify_all()
        return {"ok": True}, {}

    def _cmd_kv_get(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        key = h["key"]
        with self._cv:
            if h.get("block"):
                if not self._cv.wait_for(
                    lambda: key in self._kv, timeout=h.get("timeout")
                ):
                    return {"ok": False, "error": f"kv_get timeout on {key!r}"}, {}
            if key not in self._kv:
                return {"ok": True, "found": False}, {}
            meta, arrays = self._kv[key]
            return {"ok": True, "found": True, **meta}, arrays

    def _cmd_workload_init(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        with self._cv:
            if self._pool is None:
                self._pool = WorkloadPool(h["items"])
        return {"ok": True}, {}

    def _pool_or_raise(self) -> WorkloadPool:
        # explicit raise, not assert: must hold under ``python -O`` and
        # surface a clear remote error to a mis-ordered client
        if self._pool is None:
            raise RuntimeError("workload_init must be called first")
        return self._pool

    def _clock_or_raise(self) -> SSPClock:
        if self._clock is None:
            raise RuntimeError("ssp_init must be called first")
        return self._clock

    def _cmd_workload_fetch(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        pool = self._pool_or_raise()
        return {"ok": True, "workload": pool.fetch(int(h["worker"]))}, {}

    def _cmd_workload_finish(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        self._pool_or_raise().finish(h["workload"])
        return {"ok": True}, {}

    def _cmd_workload_stats(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        pool = self._pool_or_raise()
        return {"ok": True, "stats": pool.stats(), "all_done": pool.all_done}, {}

    def _cmd_workload_reassign(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        """Requeue workloads of a dead worker and/or stragglers by age
        (ref: WorkloadPool straggler/dead reassignment, driven by the
        scheduler's dead-node list)."""
        pool = self._pool_or_raise()
        requeued: list[str] = []
        if h.get("worker") is not None:
            requeued += pool.reassign_worker(int(h["worker"]))
        if h.get("older_than") is not None:
            requeued += pool.reassign_stragglers(float(h["older_than"]))
        return {"ok": True, "requeued": requeued}, {}

    def _cmd_progress(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        with self._cv:
            self._progress[int(h["worker"])] = h["record"]
        return {"ok": True}, {}

    def _cmd_progress_merged(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        with self._cv:
            reports = [dict(r) for r in self._progress.values()]
        return {"ok": True, "merged": merge_progress(reports)}, {}

    def _cmd_beat(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        self._monitor.beat(int(h["node_id"]), h.get("stats"))
        return {"ok": True}, {}

    def _cmd_telemetry(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        """Cluster telemetry (ref: the scheduler's dashboard, reborn):
        every node's last heartbeat piggybacked a counters+histograms
        snapshot; this merges them — plus the coordinator's own process
        — into one cluster view, and returns the per-node detail."""
        with self._cv:
            registry = {int(k): dict(v) for k, v in self._nodes.items()}
        per_node: dict[str, dict[str, Any]] = {}
        node_snaps: list[dict[str, Any]] = []
        for nid, stats in self._monitor.latest_stats().items():
            stats = dict(stats)
            tel = stats.pop("telemetry", None)
            info = registry.get(nid, {})
            per_node[str(nid)] = {
                "role": info.get("role", "?"),
                "rank": info.get("rank"),
                "stats": stats,
                "telemetry": tel,
            }
            if tel:
                node_snaps.append(tel)
        local = telemetry_snapshot()  # the coordinator's own process
        return {
            "ok": True,
            "nodes": per_node,
            "coordinator": local,
            "merged": merge_telemetry(node_snaps + [local]),
        }, {}

    def _cmd_dead(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        return {"ok": True, "dead": self._monitor.dead(), "alive": self._monitor.alive()}, {}

    def _cmd_recovered(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        """Worker ranks the recovery sweep has already handled (requeued +
        clock-retired); the scheduler merges these instead of running its
        own dead-worker logic."""
        with self._cv:
            return {
                "ok": True,
                "recovered": {str(r): dict(v) for r, v in self._recovered.items()},
            }, {}

    def _cmd_ssp_init(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        with self._cv:
            if self._clock is None:
                self._clock = SSPClock(int(h["num_workers"]), int(h["max_delay"]))
        return {"ok": True}, {}

    def _cmd_ssp_wait(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        clock = self._clock_or_raise()
        ok = clock.wait(int(h["worker"]), int(h["step"]), h.get("timeout"))
        return {"ok": True, "granted": ok}, {}

    def _cmd_ssp_finish(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        self._clock_or_raise().finish(int(h["worker"]), int(h["step"]))
        return {"ok": True}, {}

    def _cmd_ssp_retire(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        self._clock_or_raise().retire(int(h["worker"]))
        return {"ok": True}, {}

    def _cmd_ssp_progress(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        return {"ok": True, **self._clock_or_raise().progress()}, {}

    def _cmd_shutdown(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        raise RpcServer.Shutdown

    def stop(self) -> None:
        self._sweep_stop.set()
        if self._sweep_thread is not None:
            self._sweep_thread.join(timeout=5)
            self._sweep_thread = None
        self.server.stop()


class ControlClient(RpcClient):
    """Typed convenience wrapper over the coordinator's commands."""

    def register(self, role: str, **fields: Any) -> int:
        rep, _ = self.call("register", role=role, **fields)
        return int(rep["node_id"])

    def barrier(self, name: str, count: int, timeout: float | None = None) -> None:
        rep, _ = self.call("barrier", name=name, count=count, timeout=timeout)
        if not rep["ok"]:  # pragma: no cover - timeout path
            raise TimeoutError(f"barrier {name!r} timed out")

    def kv_set(self, key: str, arrays: Arrays | None = None, **fields: Any) -> None:
        self.call("kv_set", arrays=arrays, key=key, fields=fields)

    def kv_get(
        self, key: str, block: bool = False, timeout: float | None = None
    ) -> tuple[dict[str, Any], Arrays] | None:
        rep, arrays = self.call("kv_get", key=key, block=block, timeout=timeout)
        if not rep.get("found"):
            return None
        return rep.get("fields", {}), arrays

    def workload_init(self, items: list[str]) -> None:
        self.call("workload_init", items=items)

    def workload_fetch(self, worker: int) -> str | None:
        rep, _ = self.call("workload_fetch", worker=worker)
        return rep["workload"]

    def workload_finish(self, workload: str) -> None:
        self.call("workload_finish", workload=workload)

    def workload_all_done(self) -> bool:
        rep, _ = self.call("workload_stats")
        return bool(rep["all_done"])

    def workload_stats(self) -> dict[str, int]:
        rep, _ = self.call("workload_stats")
        return rep["stats"]

    def workload_reassign(
        self, worker: int | None = None, older_than: float | None = None
    ) -> list[str]:
        rep, _ = self.call(
            "workload_reassign", worker=worker, older_than=older_than
        )
        return rep["requeued"]

    def nodes(self) -> dict[str, dict[str, Any]]:
        """Registry snapshot; keys are node-id strings (JSON wire)."""
        rep, _ = self.call("nodes")
        return rep["nodes"]

    def dead_nodes(self) -> tuple[list[int], list[int]]:
        rep, _ = self.call("dead")
        return rep["dead"], rep["alive"]

    def recovered_workers(self) -> dict[int, dict[str, Any]]:
        """Worker ranks the coordinator's recovery sweep has handled."""
        rep, _ = self.call("recovered")
        return {int(r): v for r, v in rep["recovered"].items()}

    def progress(self, worker: int, record: dict[str, Any]) -> None:
        self.call("progress", worker=worker, record=record)

    def progress_merged(self) -> dict[str, Any]:
        rep, _ = self.call("progress_merged")
        return rep["merged"]

    def beat(self, node_id: int, stats: dict | None = None) -> None:
        self.call("beat", node_id=node_id, stats=stats)

    def telemetry(self) -> dict[str, Any]:
        """Cluster telemetry: per-node snapshots + the merged view
        (counters summed, latency histograms merged bucket-wise)."""
        rep, _ = self.call("telemetry")
        return {k: rep[k] for k in ("nodes", "coordinator", "merged")}

    def ssp_init(self, num_workers: int, max_delay: int) -> None:
        self.call("ssp_init", num_workers=num_workers, max_delay=max_delay)

    def ssp_wait(self, worker: int, step: int, timeout: float | None = None) -> bool:
        rep, _ = self.call("ssp_wait", worker=worker, step=step, timeout=timeout)
        return bool(rep["granted"])

    def ssp_finish(self, worker: int, step: int) -> None:
        self.call("ssp_finish", worker=worker, step=step)

    def ssp_retire(self, worker: int) -> None:
        self.call("ssp_retire", worker=worker)

    def shutdown_server(self) -> None:
        """Ask the remote RpcServer to stop (after acking)."""
        self.call("shutdown")
