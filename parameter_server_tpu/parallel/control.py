"""Host-side control plane over TCP: the cross-process tier.

Reference analog: src/system/van.* + postoffice.* — ZeroMQ sockets carrying
protobuf ``Task`` headers plus raw ``SArray`` payloads, dispatched to
Customers; the scheduler holds the node registry, barriers, heartbeats and
merged progress.

On a TPU pod the *data plane* is XLA collectives (parallel/spmd.py) and this
layer is deliberately NOT on it. What genuinely remains host-side —
scheduler traffic (node registry, barriers, the SSP clock, the workload
pool, progress merging, heartbeats, small blob exchange) — rides this tiny
TCP layer, exactly the role SURVEY.md §5.8 assigns to "jax.distributed's KV
store / a tiny host TCP layer". It is also the transport the cross-slice
(DCN) push/pull tier builds on (parallel/multislice.py), where the
reference's message filters become meaningful again.

Wire format (ref: Message = Task proto header + SArray payloads):

    u32 header_len | u32 payload_len | header JSON | payload bytes

The header carries the command and scalar fields; ``arrays`` in the header
describes the (name, dtype, shape) of each contiguous numpy payload. With
``zip`` set the payload block is zlib-compressed (ref: the compressing
filter, src/filter/compressing.h — byte compression earns its place back on
a real wire).
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
import zlib
from typing import Any, Callable

import numpy as np

from parameter_server_tpu.parallel.ssp import SSPClock
from parameter_server_tpu.parallel.workload import WorkloadPool
from parameter_server_tpu.utils.heartbeat import HeartbeatMonitor
from parameter_server_tpu.utils.metrics import merge_progress

_LEN = struct.Struct("<II")

Arrays = dict[str, np.ndarray]


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        k = sock.recv_into(view[got:], n - got)
        if k == 0:
            raise ConnectionError("peer closed")
        got += k
    return bytes(buf)


def send_frame(
    sock: socket.socket, header: dict[str, Any], arrays: Arrays | None = None
) -> int:
    """Send one framed message; returns bytes put on the wire (ref: the
    Postoffice per-message byte counters)."""
    arrays = arrays or {}
    metas = []
    chunks = []
    for name, a in arrays.items():
        a = np.ascontiguousarray(a)
        metas.append([name, a.dtype.str, list(a.shape)])
        chunks.append(a.tobytes())
    payload = b"".join(chunks)
    if header.get("zip"):
        payload = zlib.compress(payload, level=1)
    h = dict(header)
    h["arrays"] = metas
    hb = json.dumps(h).encode()
    frame = _LEN.pack(len(hb), len(payload)) + hb + payload
    sock.sendall(frame)
    return len(frame)


def recv_frame_sized(
    sock: socket.socket,
) -> tuple[dict[str, Any], Arrays, int]:
    """recv_frame plus the frame's wire size (for traffic counters)."""
    hlen, plen = _LEN.unpack(_recv_exact(sock, _LEN.size))
    header = json.loads(_recv_exact(sock, hlen))
    payload = _recv_exact(sock, plen) if plen else b""
    nbytes = _LEN.size + hlen + plen
    if header.get("zip"):
        payload = zlib.decompress(payload)
    arrays: Arrays = {}
    off = 0
    for name, dtype, shape in header.pop("arrays", []):
        dt = np.dtype(dtype)
        n = int(np.prod(shape)) if shape else 1
        nb = n * dt.itemsize
        arrays[name] = np.frombuffer(
            payload, dtype=dt, count=n, offset=off
        ).reshape(shape)
        off += nb
    return header, arrays, nbytes


def recv_frame(sock: socket.socket) -> tuple[dict[str, Any], Arrays]:
    header, arrays, _ = recv_frame_sized(sock)
    return header, arrays


class RpcServer:
    """Thread-per-connection TCP server dispatching framed requests to a
    handler (shared by the Coordinator and the shard servers). The handler
    may raise ``Shutdown`` to stop the server after replying."""

    class Shutdown(Exception):
        pass

    def __init__(
        self,
        handler: Callable[[dict[str, Any], Arrays], tuple[dict[str, Any], Arrays]],
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self._handler = handler
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.address = f"{host}:{self._sock.getsockname()[1]}"
        self._stop = threading.Event()
        self.bytes_in = 0
        self.bytes_out = 0
        self._counter_lock = threading.Lock()  # counters shared by conn threads
        self._accept_thread: threading.Thread | None = None

    def start(self) -> "RpcServer":
        self._accept_thread = threading.Thread(target=self._accept, daemon=True)
        self._accept_thread.start()
        return self

    def _accept(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # socket closed by stop()
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while True:
                header, arrays, nbytes = recv_frame_sized(conn)
                with self._counter_lock:
                    self.bytes_in += nbytes
                try:
                    rep, rep_arrays = self._handler(header, arrays)
                except RpcServer.Shutdown:
                    send_frame(conn, {"ok": True})
                    self.stop()
                    return
                except Exception as e:  # surface handler errors to the caller
                    rep, rep_arrays = {"ok": False, "error": repr(e)}, {}
                sent = send_frame(conn, rep, rep_arrays)
                with self._counter_lock:
                    self.bytes_out += sent
        except (ConnectionError, OSError):
            return  # client went away; its requests died with it

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


class RpcClient:
    """One persistent connection; requests are serialized under a lock
    (the reference's per-remote-node send queue discipline)."""

    def __init__(self, address: str, retries: int = 50, retry_delay: float = 0.1):
        host, port = address.rsplit(":", 1)
        last: Exception | None = None
        for _ in range(retries):
            try:
                self._sock = socket.create_connection((host, int(port)), timeout=30)
                break
            except OSError as e:  # server may still be binding
                last = e
                time.sleep(retry_delay)
        else:
            raise ConnectionError(f"cannot reach {address}: {last}")
        # blocking calls (barrier, ssp_wait) may legitimately park for longer
        # than any fixed socket timeout; request-level timeouts are carried in
        # the header and enforced server-side, the launcher is the backstop
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()
        self.bytes_out = 0
        self.bytes_in = 0

    def call(
        self, cmd: str, arrays: Arrays | None = None, **fields: Any
    ) -> tuple[dict[str, Any], Arrays]:
        header = {"cmd": cmd, **fields}
        with self._lock:
            self.bytes_out += send_frame(self._sock, header, arrays)
            rep, rep_arrays, nbytes = recv_frame_sized(self._sock)
            self.bytes_in += nbytes
        if not rep.get("ok", True):
            raise RuntimeError(f"{cmd} failed remotely: {rep.get('error')}")
        return rep, rep_arrays

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class Coordinator:
    """The scheduler endpoint (ref: Postoffice on the scheduler node).

    Owns: node registry, named barriers, a blob KV (small host arrays),
    the workload pool, merged progress, heartbeats, and the SSP clock.
    All commands are served by ``RpcServer`` threads; blocking commands
    (barrier / blocking kv_get / ssp_wait) park the connection's thread.
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        heartbeat_timeout_s: float = 30.0,
    ):
        self._nodes: dict[int, dict[str, Any]] = {}
        self._next_id = 0
        self._barriers: dict[str, list[int]] = {}  # name -> [arrived, generation]
        self._kv: dict[str, tuple[dict, Arrays]] = {}
        self._pool: WorkloadPool | None = None
        self._progress: dict[int, dict[str, Any]] = {}
        self._monitor = HeartbeatMonitor(heartbeat_timeout_s)
        self._clock: SSPClock | None = None
        self._cv = threading.Condition()
        self.server = RpcServer(self._handle, host, port).start()
        self.address = self.server.address

    # -- dispatch --------------------------------------------------------

    def _handle(
        self, header: dict[str, Any], arrays: Arrays
    ) -> tuple[dict[str, Any], Arrays]:
        cmd = header.pop("cmd")
        fn = getattr(self, f"_cmd_{cmd}", None)
        if fn is None:
            raise ValueError(f"unknown control command {cmd!r}")
        return fn(header, arrays)

    def _cmd_register(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        with self._cv:
            node_id = self._next_id
            self._next_id += 1
            self._nodes[node_id] = {"role": h.get("role", "?"), **h}
            self._cv.notify_all()
        return {"ok": True, "node_id": node_id}, {}

    def _cmd_nodes(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        with self._cv:
            # copy: serialization happens after the lock is released, and a
            # concurrent register mutating the live dict mid-dumps would
            # kill the connection thread
            return {"ok": True, "nodes": dict(self._nodes)}, {}

    def _cmd_barrier(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        """Block until ``count`` callers reach barrier ``name`` (ref:
        Postoffice::Barrier over node groups)."""
        name, count = h["name"], int(h["count"])
        with self._cv:
            st = self._barriers.setdefault(name, [0, 0])
            st[0] += 1
            if st[0] >= count:
                st[0] = 0
                st[1] += 1
                self._cv.notify_all()
                return {"ok": True}, {}
            gen = st[1]
            ok = self._cv.wait_for(
                lambda: self._barriers[name][1] > gen, timeout=h.get("timeout")
            )
            if not ok and self._barriers[name][1] == gen:
                st[0] -= 1  # withdraw our arrival: a later generation must
                # not release early on a participant that already gave up
        return {"ok": ok, "error": "barrier timeout" if not ok else None}, {}

    def _cmd_kv_set(self, h: dict, arrays: Arrays) -> tuple[dict, Arrays]:
        with self._cv:
            self._kv[h["key"]] = ({"fields": h.get("fields", {})}, arrays)
            self._cv.notify_all()
        return {"ok": True}, {}

    def _cmd_kv_get(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        key = h["key"]
        with self._cv:
            if h.get("block"):
                if not self._cv.wait_for(
                    lambda: key in self._kv, timeout=h.get("timeout")
                ):
                    return {"ok": False, "error": f"kv_get timeout on {key!r}"}, {}
            if key not in self._kv:
                return {"ok": True, "found": False}, {}
            meta, arrays = self._kv[key]
            return {"ok": True, "found": True, **meta}, arrays

    def _cmd_workload_init(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        with self._cv:
            if self._pool is None:
                self._pool = WorkloadPool(h["items"])
        return {"ok": True}, {}

    def _pool_or_raise(self) -> WorkloadPool:
        # explicit raise, not assert: must hold under ``python -O`` and
        # surface a clear remote error to a mis-ordered client
        if self._pool is None:
            raise RuntimeError("workload_init must be called first")
        return self._pool

    def _clock_or_raise(self) -> SSPClock:
        if self._clock is None:
            raise RuntimeError("ssp_init must be called first")
        return self._clock

    def _cmd_workload_fetch(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        pool = self._pool_or_raise()
        return {"ok": True, "workload": pool.fetch(int(h["worker"]))}, {}

    def _cmd_workload_finish(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        self._pool_or_raise().finish(h["workload"])
        return {"ok": True}, {}

    def _cmd_workload_stats(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        pool = self._pool_or_raise()
        return {"ok": True, "stats": pool.stats(), "all_done": pool.all_done}, {}

    def _cmd_workload_reassign(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        """Requeue workloads of a dead worker and/or stragglers by age
        (ref: WorkloadPool straggler/dead reassignment, driven by the
        scheduler's dead-node list)."""
        pool = self._pool_or_raise()
        requeued: list[str] = []
        if h.get("worker") is not None:
            requeued += pool.reassign_worker(int(h["worker"]))
        if h.get("older_than") is not None:
            requeued += pool.reassign_stragglers(float(h["older_than"]))
        return {"ok": True, "requeued": requeued}, {}

    def _cmd_progress(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        with self._cv:
            self._progress[int(h["worker"])] = h["record"]
        return {"ok": True}, {}

    def _cmd_progress_merged(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        with self._cv:
            reports = [dict(r) for r in self._progress.values()]
        return {"ok": True, "merged": merge_progress(reports)}, {}

    def _cmd_beat(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        self._monitor.beat(int(h["node_id"]), h.get("stats"))
        return {"ok": True}, {}

    def _cmd_dead(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        return {"ok": True, "dead": self._monitor.dead(), "alive": self._monitor.alive()}, {}

    def _cmd_ssp_init(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        with self._cv:
            if self._clock is None:
                self._clock = SSPClock(int(h["num_workers"]), int(h["max_delay"]))
        return {"ok": True}, {}

    def _cmd_ssp_wait(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        clock = self._clock_or_raise()
        ok = clock.wait(int(h["worker"]), int(h["step"]), h.get("timeout"))
        return {"ok": True, "granted": ok}, {}

    def _cmd_ssp_finish(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        self._clock_or_raise().finish(int(h["worker"]), int(h["step"]))
        return {"ok": True}, {}

    def _cmd_ssp_retire(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        self._clock_or_raise().retire(int(h["worker"]))
        return {"ok": True}, {}

    def _cmd_ssp_progress(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        return {"ok": True, **self._clock_or_raise().progress()}, {}

    def _cmd_shutdown(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        raise RpcServer.Shutdown

    def stop(self) -> None:
        self.server.stop()


class ControlClient(RpcClient):
    """Typed convenience wrapper over the coordinator's commands."""

    def register(self, role: str, **fields: Any) -> int:
        rep, _ = self.call("register", role=role, **fields)
        return int(rep["node_id"])

    def barrier(self, name: str, count: int, timeout: float | None = None) -> None:
        rep, _ = self.call("barrier", name=name, count=count, timeout=timeout)
        if not rep["ok"]:  # pragma: no cover - timeout path
            raise TimeoutError(f"barrier {name!r} timed out")

    def kv_set(self, key: str, arrays: Arrays | None = None, **fields: Any) -> None:
        self.call("kv_set", arrays=arrays, key=key, fields=fields)

    def kv_get(
        self, key: str, block: bool = False, timeout: float | None = None
    ) -> tuple[dict[str, Any], Arrays] | None:
        rep, arrays = self.call("kv_get", key=key, block=block, timeout=timeout)
        if not rep.get("found"):
            return None
        return rep.get("fields", {}), arrays

    def workload_init(self, items: list[str]) -> None:
        self.call("workload_init", items=items)

    def workload_fetch(self, worker: int) -> str | None:
        rep, _ = self.call("workload_fetch", worker=worker)
        return rep["workload"]

    def workload_finish(self, workload: str) -> None:
        self.call("workload_finish", workload=workload)

    def workload_all_done(self) -> bool:
        rep, _ = self.call("workload_stats")
        return bool(rep["all_done"])

    def workload_stats(self) -> dict[str, int]:
        rep, _ = self.call("workload_stats")
        return rep["stats"]

    def workload_reassign(
        self, worker: int | None = None, older_than: float | None = None
    ) -> list[str]:
        rep, _ = self.call(
            "workload_reassign", worker=worker, older_than=older_than
        )
        return rep["requeued"]

    def nodes(self) -> dict[str, dict[str, Any]]:
        """Registry snapshot; keys are node-id strings (JSON wire)."""
        rep, _ = self.call("nodes")
        return rep["nodes"]

    def dead_nodes(self) -> tuple[list[int], list[int]]:
        rep, _ = self.call("dead")
        return rep["dead"], rep["alive"]

    def progress(self, worker: int, record: dict[str, Any]) -> None:
        self.call("progress", worker=worker, record=record)

    def progress_merged(self) -> dict[str, Any]:
        rep, _ = self.call("progress_merged")
        return rep["merged"]

    def beat(self, node_id: int, stats: dict | None = None) -> None:
        self.call("beat", node_id=node_id, stats=stats)

    def ssp_init(self, num_workers: int, max_delay: int) -> None:
        self.call("ssp_init", num_workers=num_workers, max_delay=max_delay)

    def ssp_wait(self, worker: int, step: int, timeout: float | None = None) -> bool:
        rep, _ = self.call("ssp_wait", worker=worker, step=step, timeout=timeout)
        return bool(rep["granted"])

    def ssp_finish(self, worker: int, step: int) -> None:
        self.call("ssp_finish", worker=worker, step=step)

    def ssp_retire(self, worker: int) -> None:
        self.call("ssp_retire", worker=worker)

    def shutdown_server(self) -> None:
        """Ask the remote RpcServer to stop (after acking)."""
        self.call("shutdown")
