"""Host-side control plane over TCP: the cross-process tier.

Reference analog: src/system/van.* + postoffice.* — ZeroMQ sockets carrying
protobuf ``Task`` headers plus raw ``SArray`` payloads, dispatched to
Customers; the scheduler holds the node registry, barriers, heartbeats and
merged progress.

On a TPU pod the *data plane* is XLA collectives (parallel/spmd.py) and this
layer is deliberately NOT on it. What genuinely remains host-side —
scheduler traffic (node registry, barriers, the SSP clock, the workload
pool, progress merging, heartbeats, small blob exchange) — rides this tiny
TCP layer, exactly the role SURVEY.md §5.8 assigns to "jax.distributed's KV
store / a tiny host TCP layer". It is also the transport the cross-slice
(DCN) push/pull tier builds on (parallel/multislice.py), where the
reference's message filters become meaningful again.

Wire format (ref: Message = Task proto header + SArray payloads):

    u32 header_len | u32 payload_len | header bytes | payload bytes

The header carries the command and scalar fields; ``arrays`` in the header
describes the (name, dtype, shape, compressed_len) of each contiguous numpy
payload chunk. Header bytes come in TWO self-describing codecs, sniffed by
the first byte: ``{`` (0x7B) is the JSON codec every version understands;
``0xB7`` opens the versioned fixed-layout BINARY codec (struct-packed
magic / version / flags / cmd-id / seq / cid / array-descriptor table,
with a JSON tail for residual fields). Binary is negotiated per
connection: a client that prefers it sends JSON requests carrying
``_bh: 1`` until a reply confirms the peer decodes binary (the reply is
binary, or JSON carrying ``_bh: 1``); only then does the connection
switch — so a mixed-version cluster degrades to JSON instead of
crashing an old peer. Servers simply echo the request's codec.

Serving-plane fields (binary header version 2, ISSUE 7): every shard
pull reply carries the range's RCU publish version (``ver``); a client
holding a cached copy pulls conditionally with ``if_newer=<version>``
and an unchanged shard answers ``not_modified`` — no row payload at
all. Under overload a server may *shed* a revalidation the client
flagged ``shed_ok`` (it holds a within-bounds cached fallback) with a
``retry_after_ms`` hint instead of queueing the encode. ``ver`` /
``if_newer`` / ``not_modified`` ride fixed binary slots (they're on
every serving pull); the rare shed fields ride the JSON tail.

Optional wire FEATURES (e.g. the quantized push codec, ``"qwire"``)
negotiate per connection the same way: a client constructed with
``features`` advertises them in a ``_feat`` header list (riding the
binary codec's JSON tail) until a reply acks the intersection the server
supports; ``RpcClient.peer_features`` is empty until then, so a feature
user (ServerHandle's quantizer) stays on the baseline encoding against a
peer that never acks — mixed clusters degrade, never corrupt. Like the
codec advert, the negotiation restarts on every reconnect, so a
downgraded replacement server demotes the connection automatically.

The payload path is zero-copy end to end: ``send_frame``
gathers the length word, the header, and each array's ``memoryview``
straight into ``socket.sendmsg`` (no ``tobytes``/``join`` concatenation),
and the receiver lands the whole payload in ONE preallocated buffer that
``np.frombuffer`` views without copying. With ``zip`` set, compression is
per-array and adaptive (ref: the compressing filter,
src/filter/compressing.h): integer key lists and quantized int8/int16
payloads stay raw, arrays under a size floor stay raw, and larger float
arrays are compressed only when a sampled probe says zlib actually wins —
the bytes saved (and probes that declined) land in the process-global
``wire_bytes_saved`` / ``wire_comp_skipped`` counters (ref: the Postoffice
per-filter byte counters).

Pipelining: ``RpcClient.call_async`` keeps up to ``window`` seq-numbered
requests in flight per connection; a reader thread completes their futures
as replies arrive (matched by the ``_rseq`` echo). ``call`` is now just
``call_async(...).result()`` — so N threads sharing one client overlap
their round trips instead of serializing under a lock.

Delivery semantics (ref: the paper's vector-clock idempotent
retransmission, rebuilt for this wire format): every ``RpcClient`` request
carries a client id + sequence number; on a mid-call socket error or
truncated frame the client transparently reconnects (exponential backoff +
jitter) and *resends the same sequence number*. The server keeps a small
per-client reply cache, so a resent or duplicated non-idempotent command
(``workload_fetch``, ``ssp_finish``, ``barrier`` arrivals, pushes) is
answered from the cache instead of double-applied — at-least-once delivery
on the wire, exactly-once application at the handler.
"""

from __future__ import annotations

import json
import random
import socket
import struct
import threading
import time
import uuid
import zlib
from collections import OrderedDict, deque
from concurrent.futures import Future
from typing import Any, Callable

import numpy as np

from parameter_server_tpu.parallel.chaos import FaultPlan
from parameter_server_tpu.parallel.ssp import SSPClock
from parameter_server_tpu.parallel.workload import WorkloadPool
from parameter_server_tpu.utils import flightrec, trace
from parameter_server_tpu.utils.flightrec import watchdog
from parameter_server_tpu.utils.heartbeat import HeartbeatMonitor
from parameter_server_tpu.utils.metrics import (
    Histogram,
    hist_percentile,
    latency_histograms,
    merge_progress,
    merge_telemetry,
    race_track,
    slow_ops,
    telemetry_snapshot,
    wire_counters,
)

_LEN = struct.Struct("<II")

Arrays = dict[str, np.ndarray]

# adaptive per-array compression (the compressing filter, rebuilt):
_COMP_MIN_BYTES = 1024  # arrays below this floor are never worth the CPU
_COMP_PROBE_BYTES = 4096  # sampled-ratio window for large arrays
_COMP_PROBE_RATIO = 0.9  # the probe must beat this or the array stays raw


def _recv_exact(sock: socket.socket, n: int) -> memoryview:
    """Read exactly ``n`` bytes into ONE preallocated buffer and return a
    view of it — no trailing ``bytes(buf)`` copy; ``np.frombuffer`` on the
    receive side views this buffer directly."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        k = sock.recv_into(view[got:], n - got)
        if k == 0:
            raise ConnectionError("peer closed")
        got += k
    return view


class FrameReader:
    """Buffered socket reads for a frame stream. Small reads (length
    words, headers, small payloads) are served from one shared buffer
    filled by large recv calls — ~1 syscall per small frame instead of 3,
    and a burst of pipelined replies often lands in ONE recv. Reads with
    an empty buffer that exceed its capacity fall through to a direct
    ``recv_into`` (multi-MiB payloads keep the single-landing-buffer
    zero-copy path with no intermediate hop).

    Duck-typed as the ``recv_into`` side of a socket so
    ``recv_frame_sized`` accepts either; each reader owns ONE stream
    (the per-connection reader threads), never a shared socket."""

    __slots__ = ("_sock", "_buf", "_lo", "_hi")

    def __init__(self, sock: socket.socket, cap: int = 1 << 16):
        self._sock = sock
        self._buf = memoryview(bytearray(cap))
        self._lo = 0
        self._hi = 0

    def buffered(self) -> bool:
        """More bytes already landed? (The server's reply-coalescing cue:
        while requests are queued in the buffer, replies batch into one
        gather write; the moment input drains, replies flush — so a
        lockstep caller never waits on a withheld reply.)"""
        return self._hi > self._lo

    def recv_into(self, view, n: int) -> int:
        avail = self._hi - self._lo
        if avail == 0:
            if n >= len(self._buf):
                return self._sock.recv_into(view, n)  # big read: direct
            self._lo = 0
            k = self._sock.recv_into(self._buf)
            if k == 0:
                return 0
            self._hi = k
            avail = k
        take = min(avail, n)
        view[:take] = self._buf[self._lo : self._lo + take]
        self._lo += take
        return take


def _compressible(a: np.ndarray) -> bool:
    """Only real-float payloads above the floor are candidates: integer key
    lists and quantized int8/int16 (and f16) chunks are already dense."""
    return a.dtype.kind == "f" and a.itemsize >= 4 and a.nbytes >= _COMP_MIN_BYTES


def _try_compress(view) -> bytes | None:
    """zlib level-1 with an adaptive probe: sample the head of a large
    array first — random float32 gradients cost CPU for ~0% savings, so an
    unpromising ratio skips the full pass. Returns None to send raw."""
    n = len(view)
    if n > _COMP_PROBE_BYTES:
        probe = zlib.compress(view[:_COMP_PROBE_BYTES], 1)
        if len(probe) > _COMP_PROBE_RATIO * _COMP_PROBE_BYTES:
            wire_counters.inc("wire_comp_skipped")
            return None
    comp = zlib.compress(view, 1)
    if len(comp) >= n:
        wire_counters.inc("wire_comp_skipped")
        return None
    return comp


# ---------------------------------------------------------------------------
# binary header codec (versioned fixed layout; ref: the protobuf Task header
# the reference packed instead of a text format). json.dumps/json.loads on
# every frame was a visible share of small-frame cost once the payload path
# went zero-copy — the codec replaces it for the fields every data-plane
# frame carries, with a JSON tail for anything else.
# ---------------------------------------------------------------------------

_BMAGIC = 0xB7  # first header byte; JSON always starts with '{' (0x7B)
# version 2 = version 1 + the serving-plane flags2 slots (ver / if_newer
# / not_modified). Flag evolution is append-only: a v1 frame never sets
# the new bits, so the v2 decoder reads both layouts; the version byte
# still hard-rejects anything newer than this build understands.
_BVERSION = 2
# version 3 = version 2 + the freshness plane (ISSUE 17). Both flag
# bytes were full, so v3 adds STRUCTURE instead of bits: a third flags
# byte rides immediately after the fixed prefix, gating the publish-ts
# and realized-age slots a freshness-stamped pull reply carries. The
# lowest-version stamping rule below extends naturally — only a frame
# that actually carries a flags3 slot is stamped 3, so every other
# frame stays decodable by v1/v2 peers.
_BVERSION3 = 3
_BVERSIONS_OK = (1, 2, 3)

# flags1
_BF_CID = 1
_BF_SEQ = 2
_BF_RSEQ = 4
_BF_EXTRA = 8
_BF_OK_TRUE = 16
_BF_OK_FALSE = 32
_BF_ZIP = 64
_BF_CMD_STR = 128
# flags2
_BF2_WORKER = 1
_BF2_SIG = 2
_BF2_CODEC = 4
_BF2_NEED_KEYS = 8
_BF2_TRANSIENT = 16
# serving plane (version 2): the RCU publish version a pull reply
# carries, the client's conditional-pull floor, and the not-modified
# reply flag — first-class slots because a serving tier pays them on
# EVERY pull; the rarer shed fields (retry_after_ms, shed) ride the
# JSON tail like any residual field
_BF2_NOT_MODIFIED = 32
_BF2_VER = 64
_BF2_IF_NEWER = 128
_BF2_V2_MASK = _BF2_NOT_MODIFIED | _BF2_VER | _BF2_IF_NEWER
# flags3 (version 3; freshness plane): the wall-clock publish timestamp
# (µs since epoch) stamped at RCU publish, and the server-computed
# realized age of the data at serve time (µs). First-class slots
# because a serving tier pays them on EVERY pull reply; any
# slot-unfit value (non-int, out of range) rides the JSON tail like
# every other residual field — the codec never gates correctness.
_BF3_PTS = 1
_BF3_AGE = 2

_BFIX = struct.Struct("<BBBBBH")  # magic, version, flags1, flags2, cmd_id, narrays
_I32 = struct.Struct("<i")
_I64 = struct.Struct("<q")
_U32 = struct.Struct("<I")

#: cmd -> compact id (1-based; 0 = absent/unknown). Append-only: ids are
#: wire contract across versions.
_CMD_IDS: dict[str, int] = {
    c: i + 1
    for i, c in enumerate((
        "push", "pull", "dump", "stats", "shutdown", "register", "nodes",
        "barrier", "kv_set", "kv_get", "workload_init", "workload_fetch",
        "workload_finish", "workload_stats", "workload_reassign", "progress",
        "progress_merged", "beat", "telemetry", "dead", "recovered",
        "ssp_init", "ssp_wait", "ssp_finish", "ssp_retire", "ssp_progress",
        "echo", "audit",
    ))
}
_CMD_NAMES = {i: c for c, i in _CMD_IDS.items()}

_B1 = tuple(bytes((i,)) for i in range(256))  # single-byte length prefixes


def _vstr(s: str) -> bytes | None:
    b = s.encode()
    if len(b) > 255:
        return None
    return _B1[len(b)] + b


def _seq_bytes(v) -> bytes | None:
    if type(v) is int:
        if not (-(1 << 63) <= v < (1 << 63)):
            return None
        return b"\x00" + _I64.pack(v)
    if type(v) is str:
        vs = _vstr(v)
        return None if vs is None else b"\x01" + vs
    return None


def _encode_bin_header(h: dict[str, Any], metas: list) -> bytes | None:
    """Encode a header dict + array-descriptor table into the binary
    layout; None when a field can't be represented at all (the caller
    falls back to JSON — correctness never depends on the binary codec
    applying; a merely slot-unfit field rides the JSON tail instead).

    ``hdr_bytes_saved`` is counted against an in-loop ESTIMATE of the
    length json.dumps would have produced (running the real thing per
    frame is exactly the cost this codec removes) — accurate to a few
    bytes per frame."""
    flags1 = flags2 = flags3 = 0
    cmd_id = 0
    cmd_b = cid_b = seq_b = rseq_b = worker_b = sig_b = codec_b = None
    ver_b = ifn_b = pts_b = age_b = None
    extra: dict[str, Any] | None = None
    est = 14  # {} plus "arrays": []
    for k, v in h.items():
        if k == "cmd":
            if type(v) is not str:
                return None
            cmd_id = _CMD_IDS.get(v, 0)
            if cmd_id == 0:
                cmd_b = _vstr(v)
                if cmd_b is None:
                    return None
                flags1 |= _BF_CMD_STR
            est += 9 + len(v)
        elif k == "_cid" and type(v) is str and (cid_b := _vstr(v)) is not None:
            flags1 |= _BF_CID
            est += 10 + len(v)
        elif k == "_seq" and (seq_b := _seq_bytes(v)) is not None:
            flags1 |= _BF_SEQ
            est += 10 + (len(str(v)) if type(v) is int else len(v) + 2)
        elif k == "_rseq" and (rseq_b := _seq_bytes(v)) is not None:
            flags1 |= _BF_RSEQ
            est += 11 + (len(str(v)) if type(v) is int else len(v) + 2)
        elif k == "ok" and v is True:
            flags1 |= _BF_OK_TRUE
            est += 12
        elif k == "ok" and v is False:
            flags1 |= _BF_OK_FALSE
            est += 13
        elif k == "zip" and type(v) is bool:
            if v:
                flags1 |= _BF_ZIP
            est += 14
        elif k == "need_keys" and v is True:
            flags2 |= _BF2_NEED_KEYS
            est += 18
        elif k == "_transient" and v is True:
            flags2 |= _BF2_TRANSIENT
            est += 19
        elif (
            k == "worker" and type(v) is int and -(1 << 31) <= v < (1 << 31)
        ):
            flags2 |= _BF2_WORKER
            worker_b = _I32.pack(v)
            est += 12 + len(str(v))
        elif k == "sig" and type(v) is str and (sig_b := _vstr(v)) is not None:
            flags2 |= _BF2_SIG
            est += 9 + len(v)
        elif k == "codec" and type(v) is int and 0 <= v < 256:
            flags2 |= _BF2_CODEC
            codec_b = _B1[v]
            est += 11
        elif (
            k == "ver" and type(v) is int and 0 <= v < (1 << 63)
        ):
            flags2 |= _BF2_VER
            ver_b = _I64.pack(v)
            est += 9 + len(str(v))
        elif (
            k == "if_newer" and type(v) is int and 0 <= v < (1 << 63)
        ):
            flags2 |= _BF2_IF_NEWER
            ifn_b = _I64.pack(v)
            est += 14 + len(str(v))
        elif k == "not_modified" and v is True:
            flags2 |= _BF2_NOT_MODIFIED
            est += 21
        elif (
            k == "pts" and type(v) is int and 0 <= v < (1 << 63)
        ):
            flags3 |= _BF3_PTS
            pts_b = _I64.pack(v)
            est += 9 + len(str(v))
        elif (
            k == "_age_us" and type(v) is int and 0 <= v < (1 << 63)
        ):
            flags3 |= _BF3_AGE
            age_b = _I64.pack(v)
            est += 13 + len(str(v))
        else:
            if extra is None:
                extra = {}
            extra[k] = v
    parts: list[bytes] = [b""]  # slot 0: the fixed prefix, packed below
    if flags3:
        # the flags3 byte rides directly after the fixed prefix, BEFORE
        # the flags1/flags2 slots — a v3 decoder reads it first, then
        # falls through the shared v1/v2 slot walk
        parts.append(_B1[flags3])
    if cmd_b is not None:
        parts.append(cmd_b)
    if cid_b is not None:
        parts.append(cid_b)
    if seq_b is not None:
        parts.append(seq_b)
    if rseq_b is not None:
        parts.append(rseq_b)
    if worker_b is not None:
        parts.append(worker_b)
    if sig_b is not None:
        parts.append(sig_b)
    if codec_b is not None:
        parts.append(codec_b)
    if ver_b is not None:
        parts.append(ver_b)
    if ifn_b is not None:
        parts.append(ifn_b)
    if pts_b is not None:
        parts.append(pts_b)
    if age_b is not None:
        parts.append(age_b)
    if len(metas) > 0xFFFF:
        return None
    for name, dt, shape, clen in metas:
        nb = _vstr(name)
        db = _vstr(dt)
        if nb is None or db is None or len(shape) > 255:
            return None
        for d in shape:
            if not 0 <= d < (1 << 32):
                return None
        parts.append(nb)
        parts.append(db)
        parts.append(_B1[len(shape)])
        parts.extend(_U32.pack(d) for d in shape)
        parts.append(_U32.pack(clen))
        est += 11 + len(name) + len(dt) + len(str(clen))
        est += sum(len(str(d)) + 1 for d in shape)
    if extra is not None:
        try:
            extra_b = json.dumps(extra).encode()
        except (TypeError, ValueError):
            return None
        flags1 |= _BF_EXTRA
        parts.append(_U32.pack(len(extra_b)))
        parts.append(extra_b)
        est += len(extra_b)
    # stamp the LOWEST version whose layout this frame actually uses: a
    # frame with no v2 slots is byte-identical to a v1 frame, and
    # stamping it 1 keeps every non-serving frame decodable by v1 peers
    # (a binary-negotiated mixed cluster must degrade, not livelock —
    # the _bh ack carries no version, so the stamp is the only guard).
    # Only a frame carrying a flags3 slot is stamped 3: the freshness
    # fields are reply decoration, so a v1/v2 peer that never asked for
    # them never receives a version-3 frame either.
    ver_byte = (
        _BVERSION3 if flags3
        else _BVERSION if flags2 & _BF2_V2_MASK
        else 1
    )
    parts[0] = _BFIX.pack(
        _BMAGIC, ver_byte, flags1, flags2, cmd_id, len(metas)
    )
    out = b"".join(parts)
    wire_counters.inc_many({
        "hdr_frames_bin": 1,
        "hdr_bytes_saved": max(est - len(out), 0),
    })
    return out


def _decode_bin_header(raw: memoryview) -> dict[str, Any]:
    """Decode the binary layout back into the header dict the JSON codec
    would have produced (``arrays`` included)."""
    buf = bytes(raw)
    magic, version, flags1, flags2, cmd_id, narrays = _BFIX.unpack_from(buf, 0)
    if version not in _BVERSIONS_OK:
        raise ValueError(f"unsupported binary header version {version}")
    off = _BFIX.size
    flags3 = 0
    if version >= _BVERSION3:
        flags3 = buf[off]
        off += 1
    h: dict[str, Any] = {}
    if flags1 & _BF_CMD_STR:
        n = buf[off]
        h["cmd"] = buf[off + 1 : off + 1 + n].decode()
        off += 1 + n
    elif cmd_id:
        # a cmd id appended by a NEWER peer must degrade to an unknown
        # command (graceful ok:False reply from the handler), not a
        # KeyError that kills the serving thread
        h["cmd"] = _CMD_NAMES.get(cmd_id) or f"unknown_cmd_{cmd_id}"
    if flags1 & _BF_CID:
        n = buf[off]
        h["_cid"] = buf[off + 1 : off + 1 + n].decode()
        off += 1 + n
    if flags1 & _BF_SEQ:
        if buf[off] == 0:
            h["_seq"] = _I64.unpack_from(buf, off + 1)[0]
            off += 9
        else:
            n = buf[off + 1]
            h["_seq"] = buf[off + 2 : off + 2 + n].decode()
            off += 2 + n
    if flags1 & _BF_RSEQ:
        if buf[off] == 0:
            h["_rseq"] = _I64.unpack_from(buf, off + 1)[0]
            off += 9
        else:
            n = buf[off + 1]
            h["_rseq"] = buf[off + 2 : off + 2 + n].decode()
            off += 2 + n
    if flags2 & _BF2_WORKER:
        h["worker"] = _I32.unpack_from(buf, off)[0]
        off += 4
    if flags2 & _BF2_SIG:
        n = buf[off]
        h["sig"] = buf[off + 1 : off + 1 + n].decode()
        off += 1 + n
    if flags2 & _BF2_CODEC:
        h["codec"] = buf[off]
        off += 1
    if flags2 & _BF2_VER:
        h["ver"] = _I64.unpack_from(buf, off)[0]
        off += 8
    if flags2 & _BF2_IF_NEWER:
        h["if_newer"] = _I64.unpack_from(buf, off)[0]
        off += 8
    if flags3 & _BF3_PTS:
        h["pts"] = _I64.unpack_from(buf, off)[0]
        off += 8
    if flags3 & _BF3_AGE:
        h["_age_us"] = _I64.unpack_from(buf, off)[0]
        off += 8
    if flags1 & _BF_OK_TRUE:
        h["ok"] = True
    elif flags1 & _BF_OK_FALSE:
        h["ok"] = False
    if flags1 & _BF_ZIP:
        h["zip"] = True
    if flags2 & _BF2_NEED_KEYS:
        h["need_keys"] = True
    if flags2 & _BF2_TRANSIENT:
        h["_transient"] = True
    if flags2 & _BF2_NOT_MODIFIED:
        h["not_modified"] = True
    metas = []
    for _ in range(narrays):
        n = buf[off]
        name = buf[off + 1 : off + 1 + n].decode()
        off += 1 + n
        n = buf[off]
        dt = buf[off + 1 : off + 1 + n].decode()
        off += 1 + n
        ndim = buf[off]
        off += 1
        shape = [
            _U32.unpack_from(buf, off + 4 * i)[0] for i in range(ndim)
        ]
        off += 4 * ndim
        clen = _U32.unpack_from(buf, off)[0]
        off += 4
        metas.append([name, dt, shape, clen])
    if flags1 & _BF_EXTRA:
        n = _U32.unpack_from(buf, off)[0]
        off += 4
        h.update(json.loads(buf[off : off + n]))
        off += n
    h["arrays"] = metas
    return h


#: control-plane commands that ride the HIGH priority lane: they must
#: never queue behind a multi-MiB pull reply sharing the connection
#: (heartbeats read as death, the SSP clock stalls every worker).
#: NOT ``shutdown``: promoting it in the client writer's lane sort would
#: reorder it AHEAD of still-queued pushes on the same connection — the
#: server would stop before applying them.
_PRIO_CMDS = frozenset({
    "beat", "barrier", "register", "nodes", "dead", "recovered", "stats",
    "ssp_init", "ssp_wait", "ssp_finish", "ssp_retire",
    "ssp_progress", "workload_fetch", "workload_finish", "workload_stats",
    "workload_reassign", "audit",
})


def _send_gather(sock, bufs: list) -> None:
    """Gather-write a frame's buffers with one-or-few ``sendmsg`` calls —
    the zero-copy half of send_frame. Transports without sendmsg (test
    sinks, exotic sockets) fall back to a single joined sendall."""
    sendmsg = getattr(sock, "sendmsg", None)
    if sendmsg is None:
        sock.sendall(b"".join(bufs))
        return
    wire_counters.inc("wire_frames_zero_copy")
    views = [memoryview(b) for b in bufs if len(b)]
    while views:
        sent = sendmsg(views[:1024])  # IOV_MAX guard for coalesced batches
        while sent:  # partial gather writes happen at multi-MiB payloads
            if sent >= len(views[0]):
                sent -= len(views[0])
                views.pop(0)
            else:
                views[0] = views[0][sent:]
                sent = 0


def build_frame(
    header: dict[str, Any], arrays: Arrays | None = None,
    bin_hdr: bool = False,
) -> tuple[list, int]:
    """Encode one framed message as a list of gather buffers (length word,
    header bytes, then each array's memoryview — no tobytes/join copies)
    plus its total wire size. Callers hand the buffers to one gather
    write, possibly COALESCED with other frames' buffers (the pipelined
    client's flusher batches a window of small frames into a single
    sendmsg). With ``zip`` in the header each eligible array is
    compressed only when the adaptive probe says it wins (meta entry:
    compressed length, 0 = raw). ``bin_hdr`` uses the binary header
    codec — callers must only pass True once the peer negotiated it
    (a field the fixed layout can't carry falls back to JSON silently)."""
    arrays = arrays or {}
    metas = []
    bufs: list = []
    plen = 0
    zip_ok = bool(header.get("zip"))
    for name, a in arrays.items():
        a = np.ascontiguousarray(a)
        chunk = memoryview(a).cast("B") if a.ndim else a.tobytes()
        clen = 0
        if zip_ok and _compressible(a):
            comp = _try_compress(chunk)
            if comp is not None:
                wire_counters.inc("wire_bytes_saved", a.nbytes - len(comp))
                chunk = comp
                clen = len(comp)
        metas.append([name, a.dtype.str, list(a.shape), clen])
        bufs.append(chunk)
        plen += len(chunk)
    hb = _encode_bin_header(header, metas) if bin_hdr else None
    if hb is None:
        h = dict(header)
        h["arrays"] = metas
        hb = json.dumps(h).encode()
    nbytes = _LEN.size + len(hb) + plen
    # frame-layer byte accounting: EVERY framed message — coordinator and
    # control traffic included — lands in the process-global counters, so
    # the cluster's wire-byte columns no longer undercount to just the
    # ServerHandle data plane
    wire_counters.inc("wire_bytes_out", nbytes)
    return [_LEN.pack(len(hb), plen), hb, *bufs], nbytes


def send_frame(
    sock: socket.socket, header: dict[str, Any], arrays: Arrays | None = None
) -> int:
    """Send one framed message; returns bytes put on the wire (ref: the
    Postoffice per-message byte counters)."""
    bufs, nbytes = build_frame(header, arrays)
    _send_gather(sock, bufs)
    return nbytes


def recv_frame_ex(
    sock: socket.socket,
) -> tuple[dict[str, Any], Arrays, int, bool]:
    """recv_frame plus the frame's wire size (for traffic counters) and
    whether the header arrived in the binary codec (the receiver's half
    of per-connection codec negotiation — the first header byte is the
    sniff: ``{`` is JSON, ``_BMAGIC`` is binary).

    Raw array chunks are returned as ``np.frombuffer`` views of the single
    preallocated receive buffer — zero copies on the landing path;
    compressed chunks (meta compressed_len > 0) decompress per array."""
    hlen, plen = _LEN.unpack(_recv_exact(sock, _LEN.size))
    hraw = _recv_exact(sock, hlen)
    was_bin = hlen > 0 and hraw[0] == _BMAGIC
    if was_bin:
        header = _decode_bin_header(hraw)
    else:
        header = json.loads(hraw.tobytes())
    payload = _recv_exact(sock, plen) if plen else memoryview(b"")
    nbytes = _LEN.size + hlen + plen
    wire_counters.inc("wire_bytes_in", nbytes)  # frame layer (see send_frame)
    arrays: Arrays = {}
    off = 0
    for name, dtype, shape, clen in header.pop("arrays", []):
        dt = np.dtype(dtype)
        n = int(np.prod(shape)) if shape else 1
        if clen:
            raw = zlib.decompress(payload[off : off + clen])
            arrays[name] = np.frombuffer(raw, dtype=dt, count=n).reshape(shape)
            off += clen
        else:
            arrays[name] = np.frombuffer(
                payload, dtype=dt, count=n, offset=off
            ).reshape(shape)
            off += n * dt.itemsize
    return header, arrays, nbytes, was_bin


def recv_frame_sized(
    sock: socket.socket,
) -> tuple[dict[str, Any], Arrays, int]:
    header, arrays, nbytes, _ = recv_frame_ex(sock)
    return header, arrays, nbytes


def recv_frame(sock: socket.socket) -> tuple[dict[str, Any], Arrays]:
    header, arrays, _, _ = recv_frame_ex(sock)
    return header, arrays


class DeferredReply:
    """Handler return marker for a reply that is not ready yet: the
    ``future`` resolves to ``(rep_header, rep_arrays)`` later (the shard
    server's batched apply engine acks a push only once its batch
    applied). The serving connection thread keeps draining buffered
    requests — pulls keep flowing past queued pushes — and settles every
    deferred reply before it would block on the socket, so 'reply sent'
    still means 'side effect durable'."""

    __slots__ = ("future",)

    def __init__(self, future: Future):
        self.future = future


class _DedupEntry:
    """One cached reply. ``event`` lets a resent/duplicated frame that
    arrives while the first delivery is still being applied (e.g. parked in
    a barrier) wait for THAT application's reply instead of re-applying."""

    __slots__ = ("event", "rep", "arrays")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.rep: dict[str, Any] | None = None
        self.arrays: Arrays | None = None


# Reply-cache bounds: a pipelined client may hold a full window of
# non-idempotent requests in flight, and a reconnect resends them ALL — the
# per-client cache must cover the window (with slack for bounce re-issues)
# or a resent, already-applied push would miss the cache and double-apply.
_DEDUP_PER_CLIENT = 64
_DEDUP_CLIENTS = 1024


class RpcServer:
    """Thread-per-connection TCP server dispatching framed requests to a
    handler (shared by the Coordinator and the shard servers). The handler
    may raise ``Shutdown`` to stop the server after replying.

    Requests carrying a client id + sequence number are deduplicated
    through a per-client reply cache (see module docstring). A
    :class:`~parameter_server_tpu.parallel.chaos.FaultPlan` may be armed —
    explicitly or via the ``PS_FAULT_PLAN`` env var — to perturb received
    frames for recovery testing."""

    class Shutdown(Exception):
        pass

    def __init__(
        self,
        handler: Callable[[dict[str, Any], Arrays], tuple[dict[str, Any], Arrays]],
        host: str = "127.0.0.1",
        port: int = 0,
        fault_plan: FaultPlan | None = None,
        idempotent_cmds: frozenset[str] = frozenset(),
        expose_identity: bool = False,
        blocking_cmds: frozenset[str] = frozenset(),
        prio_cmds: frozenset[str] = _PRIO_CMDS,
        lane_hi: int = 4,
        lane_lo: int = 16,
        withheld_max_bytes: int = 8 << 20,
        features: frozenset[str] = frozenset(),
    ):
        self._handler = handler
        # optional wire features this server's handler understands (e.g.
        # "qwire"): replies ack the intersection with a client's _feat
        # advert, never more — the negotiation contract that lets a
        # quantized client degrade to floats against an old server
        self._features = frozenset(features)
        # reply priority lanes: replies to prio_cmds flush first (and at a
        # tighter withheld bound) so a control ack sharing the connection
        # never queues behind a multi-MiB coalesced pull reply
        self._prio_cmds = prio_cmds
        self._lane_hi = max(1, int(lane_hi))
        self._lane_lo = max(1, int(lane_lo))
        self._withheld_max_bytes = int(withheld_max_bytes)
        # commands whose handler may PARK the connection thread (barrier,
        # ssp_wait, blocking kv_get): coalesced replies must flush before
        # dispatching one, or earlier requests' replies would be withheld
        # for as long as the blocking command parks
        self._blocking_cmds = blocking_cmds
        # re-applying these is harmless, so resends bypass the reply cache
        # entirely — caching their (potentially large: pull/dump/kv_get
        # payloads) replies would pin the arrays of the last
        # _DEDUP_PER_CLIENT requests per client for no correctness gain
        self._idempotent_cmds = idempotent_cmds
        # hand the deduped (cid, seq) identity to the handler (as _cid/_seq
        # header fields) so it can keep its own durable dedup ledger — the
        # shard server persists applied push seqs into its checkpoint
        self._expose_identity = expose_identity
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.address = f"{host}:{self._sock.getsockname()[1]}"
        self._stop = threading.Event()
        self.bytes_in = 0
        self.bytes_out = 0
        self.frames_in = 0
        # live withheld coalesced-reply bytes across ALL connections (the
        # lo lane pins pull payloads while withheld): the serving plane's
        # load-shedding signal, distinct from the *_peak gauge telemetry
        # keeps — shedding needs the current depth, not the high-water
        self._withheld_now = 0
        self._counter_lock = threading.Lock()  # counters shared by conn threads
        self._accept_thread: threading.Thread | None = None
        self._conns: set[socket.socket] = set()  # live, for stop() to sever
        # cid -> (seq -> _DedupEntry), both LRU-bounded
        self._dedup: OrderedDict[str, OrderedDict[int, _DedupEntry]] = OrderedDict()
        self._dedup_lock = threading.Lock()
        self.fault_plan = fault_plan if fault_plan is not None else FaultPlan.from_env()

    def start(self) -> "RpcServer":
        self._accept_thread = threading.Thread(target=self._accept, daemon=True)
        self._accept_thread.start()
        return self

    def _accept(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # socket closed by stop()
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        reader = FrameReader(conn)  # this thread owns the receive side
        # reply coalescing, now in TWO priority lanes: while further
        # requests sit in the read buffer (a pipelined burst), replies
        # accumulate and flush as ONE gather write with the hi (control)
        # lane ahead of the lo (bulk) lane; with nothing buffered the
        # reply flushes immediately, so lockstep latency is untouched.
        # Reordering replies across lanes is safe: pipelined clients
        # match replies by the _rseq echo, and raw no-seq clients only
        # ever see the in-order single-reply path (both lanes flush
        # together, hi first, and a raw client gets one reply per
        # lockstep request anyway).
        hi_bufs: list = []
        lo_bufs: list = []
        hi_n = lo_n = 0
        hi_frames = lo_frames = 0
        # deferred replies (batched apply): settled before this thread
        # blocks on the socket, so an acked push is always applied;
        # entries are (seq, deferred, cmd, t_svc, bin_hdr, advert,
        # feats, trace_ctx)
        deferred: list[
            tuple[
                Any, DeferredReply, str, float, bool, bool,
                list | None, dict | None,
            ]
        ] = []

        def queue_reply(
            rep: dict[str, Any], rep_arrays: Arrays | None,
            hi: bool = False, bin_hdr: bool = False,
        ) -> None:
            nonlocal hi_n, lo_n, hi_frames, lo_frames
            fb, n = build_frame(rep, rep_arrays, bin_hdr=bin_hdr)
            # flight recorder: the reply side of the frame ledger (the
            # request side records at dispatch) — rseq is the caller's
            # seq echo, the postmortem's stitch key
            flightrec.record(
                "rpc.out", rseq=rep.get("_rseq"),
                ok=rep.get("ok", True), n=n,
            )
            if hi:
                hi_bufs.extend(fb)
                hi_n += n
                hi_frames += 1
            else:
                lo_bufs.extend(fb)
                lo_n += n
                lo_frames += 1
            # reply-coalescing memory gauge: the deepest withheld-bytes
            # point any connection reached (merged cluster-wide as a max)
            wire_counters.observe_max("wire_withheld_bytes_peak", hi_n + lo_n)
            with self._counter_lock:
                self._withheld_now += n

        def flush_replies() -> None:
            nonlocal hi_bufs, lo_bufs, hi_n, lo_n, hi_frames, lo_frames
            if not hi_bufs and not lo_bufs:
                return
            _send_gather(conn, hi_bufs + lo_bufs)  # control lane first
            with self._counter_lock:
                self.bytes_out += hi_n + lo_n
                self._withheld_now -= hi_n + lo_n
            hi_bufs, lo_bufs = [], []
            hi_n = lo_n = 0
            hi_frames = lo_frames = 0

        def decorated(
            rep: dict[str, Any], seq_d: Any, adv_d: bool,
            feat_d: list | None = None, svc_us: int | None = None,
        ) -> dict[str, Any]:
            """One copy of the reply decoration: echo the request's seq
            (``_rseq``), ack the codec advert (``_bh``) and/or the
            feature advert (``_feat``), and stamp the server-observed
            service time (``_svc_us`` — the client's latency-forensics
            planes split wall time into wire vs server from this echo)
            on a COPY — ``rep`` may be a shared reply-cache dict.

            Freshness plane (ISSUE 17): a handler that stamped its
            reply with the RCU publish timestamp (``pts``, µs epoch)
            gets the realized data age (``_age_us``) computed HERE,
            per serve — the publish ts is version-constant and may
            ride shared/cached reply dicts, but the age each consumer
            sees depends on when THIS serve happened, and the
            publish/serve clocks belong to the same process, so the
            delta is skew-free."""
            pts_d = rep.get("pts")
            if (
                seq_d is None and not adv_d and feat_d is None
                and svc_us is None and pts_d is None
            ):
                return rep
            rep = dict(rep)
            if type(pts_d) is int:
                rep["_age_us"] = max(int(time.time() * 1e6) - pts_d, 0)
            if seq_d is not None:
                rep["_rseq"] = seq_d
            if adv_d:
                rep["_bh"] = 1
            if feat_d is not None:
                rep["_feat"] = feat_d
            if svc_us is not None:
                rep["_svc_us"] = svc_us
            return rep

        def settle_deferred() -> None:
            """Resolve every pending deferred reply into the lo lane (in
            arrival order). Called before any point where this thread
            would block on the socket or sever the connection. Entries
            pop as they settle, so on the error edge below the finally
            drain sees exactly the entries whose replies were never
            queued — none stranded, none double-counted."""
            while deferred:
                seq_d, d, cmd_d, t_d, bin_d, adv_d, feat_d, tctx_d = (
                    deferred[0]
                )
                try:
                    rep_d, arrays_d = d.future.result()
                except ConnectionError:
                    # the apply engine is stopping under this push: a
                    # clean ok:False reply would read as a PERMANENT
                    # remote error and the client would never resend —
                    # sever the connection instead, so the transport heal
                    # retries against the relaunched server (the durable
                    # ledger dedups any half-applied overlap). The
                    # still-parked remainder (this entry included) is
                    # consumed by the conn teardown's finally drain.
                    flush_replies()
                    raise
                except Exception as e:  # noqa: BLE001 — surfaced remotely
                    rep_d, arrays_d = {"ok": False, "error": repr(e)}, {}
                deferred.pop(0)
                svc_d = time.perf_counter() - t_d
                latency_histograms.observe(
                    f"server.{cmd_d}", svc_d,
                    exemplar=(tctx_d or {}).get("tid"),
                )
                queue_reply(
                    decorated(
                        rep_d, seq_d, adv_d, feat_d,
                        svc_us=int(svc_d * 1e6),
                    ),
                    arrays_d, hi=False, bin_hdr=bin_d,
                )
        with self._counter_lock:
            self._conns.add(conn)
        # register-then-check pairs with stop()'s set-then-sever: a conn
        # accepted concurrently with stop() is either seen by the sweep
        # above or bails here — it can never serve a stopped server
        if self._stop.is_set():
            with self._counter_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass
            return
        try:
            while True:
                header, arrays, nbytes, was_bin = recv_frame_ex(reader)
                with self._counter_lock:
                    self.bytes_in += nbytes
                    self.frames_in += 1
                fault = (
                    self.fault_plan.decide(header.get("cmd", ""))
                    if self.fault_plan is not None
                    else None
                )
                if fault is not None and fault.action == "drop":
                    # the fault models THIS request lost on the wire, not
                    # the whole batch: earlier requests' withheld replies
                    # still go out (as they did pre-coalescing), or a
                    # periodic drop would livelock a pipelined client —
                    # every resend round re-killed before any reply lands
                    settle_deferred()
                    flush_replies()
                    return  # request lost before it applied; conn closed below
                if fault is not None and fault.action == "delay":
                    time.sleep(fault.delay_s)
                cid = header.pop("_cid", None)
                seq = header.pop("_seq", None)
                tctx = header.pop("_trace", None)  # caller's span identity
                # codec negotiation: the reply rides the request's codec
                # (echo — a binary request proves the peer decodes binary);
                # a JSON request advertising _bh gets _bh acked back so the
                # client knows it may switch this connection to binary
                advert = bool(header.pop("_bh", False)) and not was_bin
                # feature negotiation: ack the intersection of the
                # client's advertised features with what this server's
                # handler actually understands (an old client sends no
                # _feat and gets no ack; an old server leaves _feat in
                # the header, which every handler ignores)
                feat_req = header.pop("_feat", None)
                feat_ack = (
                    sorted(self._features.intersection(feat_req))
                    if isinstance(feat_req, (list, tuple))
                    else None
                )
                cmd_name = header.get("cmd", "?")
                # flight recorder: every received frame with its dedup
                # identity — what the postmortem stitches across processes
                flightrec.record(
                    "rpc.in", cmd=cmd_name, cid=cid, seq=seq, n=nbytes,
                )
                # copy BEFORE dispatch: handlers mutate the header (pop cmd)
                dup_header = (
                    dict(header)
                    if fault is not None and fault.action == "duplicate"
                    else None
                )
                if (hi_bufs or lo_bufs or deferred) and (
                    cmd_name in self._blocking_cmds
                ):
                    settle_deferred()
                    flush_replies()  # see blocking_cmds in __init__
                t_svc = time.perf_counter()
                try:
                    # activate() binds the wire-borne trace context so the
                    # dispatch span (and any handler spans under it) joins
                    # the client's trace — one logical push is one trace id
                    # across processes
                    with trace.activate(tctx), trace.span(
                        f"rpc.serve.{cmd_name}", cat="rpc", bytes_in=nbytes
                    ):
                        rep, rep_arrays = self._dispatch(
                            cid, seq, header, arrays
                        )
                        if dup_header is not None:
                            # the same frame delivered twice: without dedup
                            # this double-applies (copy's reply discarded)
                            self._dispatch(cid, seq, dup_header, arrays)
                    if not isinstance(rep, DeferredReply):
                        latency_histograms.observe(
                            f"server.{cmd_name}", time.perf_counter() - t_svc,
                            exemplar=(tctx or {}).get("tid"),
                        )
                except RpcServer.Shutdown:
                    try:
                        settle_deferred()
                        queue_reply(
                            decorated({"ok": True}, seq, advert, feat_ack),
                            None, hi=True, bin_hdr=was_bin,
                        )
                        flush_replies()
                    finally:
                        # stop() even when the ack send fails: the reply
                        # cache would answer a resent shutdown without
                        # re-running the handler, so nothing would ever
                        # stop the server (shutdown is the one command
                        # whose side effect happens after the reply)
                        self.stop()
                    return
                if fault is not None and fault.action == "disconnect":
                    # lose THIS reply only (see the drop branch): earlier
                    # withheld replies flush before the conn severs. A
                    # deferred apply is still settled first — 'disconnect'
                    # loses the reply, never the side effect's durability.
                    if isinstance(rep, DeferredReply):
                        try:
                            rep.future.result()
                        except Exception:  # noqa: BLE001 — reply is lost
                            pass
                    settle_deferred()
                    flush_replies()
                    return  # applied, but the reply is lost; conn closed below
                if isinstance(rep, DeferredReply):
                    deferred.append((
                        seq, rep, cmd_name, t_svc, was_bin, advert,
                        feat_ack, tctx,
                    ))
                    if len(deferred) >= 64:  # bound parked futures
                        settle_deferred()
                else:
                    # the seq echo lets a pipelined client match this
                    # reply to the right in-flight future
                    queue_reply(
                        decorated(
                            rep, seq, advert, feat_ack,
                            svc_us=int(
                                (time.perf_counter() - t_svc) * 1e6
                            ),
                        ),
                        rep_arrays,
                        hi=cmd_name in self._prio_cmds, bin_hdr=was_bin,
                    )
                # flush when input drains — or at a lane bound: withheld
                # pull replies pin their row arrays (frames AND bytes are
                # bounded), and control acks flush at the tighter hi bound
                if not reader.buffered():
                    settle_deferred()
                    flush_replies()
                elif (
                    lo_frames >= self._lane_lo
                    or hi_frames >= self._lane_hi
                    or hi_n + lo_n >= self._withheld_max_bytes
                ):
                    flush_replies()
        except (ConnectionError, OSError):
            return  # client went away; its requests died with it
        except (ValueError, KeyError, IndexError, struct.error, zlib.error):
            return  # undecodable frame: framing lost, sever the conn
        finally:
            # settle-exactly-once, exception edges included (pslint
            # settle-exactly-once true positive): a conn torn down by a
            # socket error or an undecodable frame may still hold parked
            # deferred replies. Their SENDS are lost with the connection
            # (the client's heal resends; the durable ledger dedups) but
            # every future is still consumed here, so a parked apply's
            # error can't vanish with the conn thread and the parked
            # result arrays drop their last reference promptly.
            for _, d, *_rest in deferred:
                wire_counters.inc("rpc_deferred_orphaned")
                try:
                    # the apply engine resolves every queued push, even
                    # at shutdown (_fail_stopping) — the timeout is a
                    # backstop, not an expected path
                    d.future.exception(timeout=30)
                except Exception:  # noqa: BLE001 — reply already lost
                    pass
            deferred.clear()
            try:
                conn.close()
            except OSError:
                pass
            with self._counter_lock:
                self._conns.discard(conn)
                # replies withheld when the conn died were never sent:
                # release their bytes from the live gauge (zero when the
                # last flush landed) so shedding can't latch on a corpse
                self._withheld_now -= hi_n + lo_n

    def _dispatch(
        self, cid: str | None, seq: int | None, header: dict[str, Any], arrays: Arrays
    ) -> tuple[dict[str, Any], Arrays]:
        """Apply-or-replay: the first delivery of (cid, seq) runs the
        handler and caches its reply; every later delivery returns that
        cached reply (waiting for it if the first is still in flight)."""
        if cid is None or seq is None:  # legacy/raw frame: no dedup contract
            return self._apply(header, arrays)
        if header.get("cmd") in self._idempotent_cmds:
            return self._apply(header, arrays)  # re-apply beats caching
        if self._expose_identity:
            header["_cid"], header["_seq"] = cid, seq
        with self._dedup_lock:
            per = self._dedup.get(cid)
            if per is None:
                per = self._dedup[cid] = OrderedDict()
                while len(self._dedup) > _DEDUP_CLIENTS:
                    self._dedup.popitem(last=False)
            else:
                self._dedup.move_to_end(cid)
            ent = per.get(seq)
            owner = ent is None
            if owner:
                ent = per[seq] = _DedupEntry()
                while len(per) > _DEDUP_PER_CLIENT:
                    per.popitem(last=False)
        if not owner:
            ent.event.wait()  # may park on a blocking command's first apply
            wire_counters.inc("rpc_dedup_hits")
            return ent.rep, ent.arrays  # type: ignore[return-value]
        try:
            rep, rep_arrays = self._apply(header, arrays)
        except RpcServer.Shutdown:
            # cache the ack a resend would expect, then let _serve stop us
            ent.rep, ent.arrays = {"ok": True}, {}
            ent.event.set()
            raise
        if not isinstance(rep, DeferredReply) and rep.get("_transient"):
            # did-not-commit reply (e.g. the shard server's need_keys
            # bounce): nothing was applied, so a later delivery of this
            # SAME (cid, seq) must re-run the handler, not replay this
            # bounce — drop the entry instead of caching it. This is what
            # lets one logical mutation keep one dedup identity across
            # the key-caching protocol's two-phase exchange.
            with self._dedup_lock:
                per = self._dedup.get(cid)
                if per is not None and per.get(seq) is ent:
                    del per[seq]
        ent.rep, ent.arrays = rep, rep_arrays
        ent.event.set()
        return rep, rep_arrays

    def _apply(
        self, header: dict[str, Any], arrays: Arrays
    ) -> tuple[dict[str, Any], Arrays]:
        try:
            return self._handler(header, arrays)
        except RpcServer.Shutdown:
            raise
        except Exception as e:  # surface handler errors to the caller
            return {"ok": False, "error": repr(e)}, {}

    def fault_stats(self) -> dict[str, int] | None:
        """Armed plan's fire counts (None when no plan is armed)."""
        return None if self.fault_plan is None else self.fault_plan.stats()

    def withheld_bytes(self) -> int:
        """Current coalesced-reply bytes withheld across every live
        connection (the serving plane's shed signal: withheld lo-lane
        replies pin their pull payload arrays until flushed)."""
        with self._counter_lock:
            return self._withheld_now

    def stop(self) -> None:
        self._stop.set()
        # shutdown BEFORE close: the accept thread parked in accept() holds
        # the open file description, so a bare close() leaves the kernel
        # socket listening forever — the port could never be rebound by a
        # restarted server and stop() would not actually stop accepting
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        # sever live connections: a stopped server must look DEAD to its
        # clients (their self-healing reconnect logic owns what happens
        # next), not leave them parked on a half-alive socket
        with self._counter_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass


class _PendingCall:
    """One in-flight request: everything needed to complete OR resend it."""

    __slots__ = ("seq", "cmd", "header", "arrays", "future", "t0", "retry", "sent")

    def __init__(
        self, seq: Any, cmd: str, header: dict[str, Any],
        arrays: Arrays | None, retry: bool,
    ):
        self.seq = seq
        self.cmd = cmd
        self.header = header
        self.arrays = arrays
        self.future: Future = Future()
        self.t0 = time.perf_counter()
        self.retry = retry
        self.sent = False  # sent on the CURRENT connection generation


class RpcClient:
    """One persistent connection carrying a bounded window of pipelined
    requests (ref: the per-remote-node send queue, now actually async).

    ``call_async`` admits up to ``window`` seq-numbered requests onto the
    wire without waiting for replies; a reader thread matches each reply
    (by the server's ``_rseq`` echo) to its future. ``call`` is
    ``call_async(...).result()`` — so concurrent callers overlap their
    round trips instead of serializing a full RTT each.

    Self-healing: every request carries this client's id and a sequence
    number. A dead connection triggers ONE heal (transparent reconnect
    with exponential backoff + jitter, bounded by ``reconnect_timeout_s``)
    that resends every pending request with its SAME sequence number — the
    server's reply cache makes the resends exactly-once even for
    non-idempotent commands, with the whole window in flight. The window
    only bounds time spent *retrying after a failure*; a healthy blocking
    call (barrier, ssp_wait) may park indefinitely as before."""

    #: completions between window adaptations (adaptive_window)
    _ADAPT_EVERY = 64

    def __init__(
        self,
        address: str,
        retries: int = 50,
        retry_delay: float = 0.1,
        reconnect_timeout_s: float = 30.0,
        cid: str | None = None,
        start_seq: int = 0,
        window: int = 8,
        hdr_codec: str = "bin",
        adaptive_window: bool = False,
        features: frozenset[str] | tuple = (),
    ):
        """``cid``/``start_seq`` transfer a logical client identity into a
        rebuilt connection (ServerHandle recovery): the server's dedup
        state is keyed by cid, so a resend after the rebuild is only
        recognized if the identity survives. ``start_seq`` must clear the
        old client's counter or fresh requests would collide with (and be
        swallowed by) cached replies of old sequence numbers.

        ``hdr_codec="bin"`` prefers the binary header codec: requests go
        JSON carrying ``_bh: 1`` until a reply proves the peer decodes
        binary, then this connection switches (re-negotiated per
        reconnect, so a downgraded replacement server degrades to JSON).

        ``adaptive_window=True`` derives the EFFECTIVE in-flight window
        from this client's completion-latency histogram: halve on a p99
        blowup, creep back up while latency is healthy and the window is
        saturated. ``window`` stays the hard ceiling.

        ``features`` are optional wire capabilities to negotiate (the
        ``_feat`` advert): ``peer_features`` stays empty until a reply
        acks what the server supports, and resets on every reconnect."""
        self._address = address
        self._cid = cid or uuid.uuid4().hex[:16]
        self._next_seq = start_seq
        self._reconnect_timeout_s = reconnect_timeout_s
        self._window = max(1, int(window))
        self._hdr_bin = hdr_codec == "bin"
        self._bin_gen_ok = False  # this connection negotiated binary
        self._rseq_gen_ok = False  # peer echoes _rseq on this connection
        self._features = frozenset(features)
        self._peer_features: frozenset[str] = frozenset()
        self._feat_gen_ok = False  # peer acked _feat on this connection
        self._adaptive = bool(adaptive_window)
        self._eff_window = self._window
        self._lat_hist = Histogram()  # this client's own completions
        self._adapt_last: dict[str, Any] | None = None
        self._adapt_n = 0
        self._adapt_peak = 0
        self._ema_p50 = 0.0
        self._completed_n = 0  # watchdog probe: replies matched to futures
        self._rng = random.Random()  # backoff jitter: no determinism contract
        self._cv = threading.Condition()  # guards all connection/pending state
        # serializes actual socket writes (inline fast path vs the writer
        # thread) WITHOUT holding _cv: a send blocked on backpressure must
        # never starve the reader completing replies
        self._send_lock = threading.Lock()
        self._pending: OrderedDict[Any, _PendingCall] = OrderedDict()
        self._closed = False
        self._healing = False
        self._gen = 0
        self._sock: socket.socket | None = None
        self.bytes_out = 0
        self.bytes_in = 0
        # lockset race witness (PS_RACE_WITNESS=1): the pipelined window
        # map and the adaptive effective window are shared by every
        # caller, the reader/writer threads and the healer — all under
        # _cv, or the whole-window resend-on-heal accounting breaks
        race_track(
            self, ("_pending", "_eff_window"), f"RpcClient:{self._cid}"
        )
        last: Exception | None = None
        for _ in range(retries):
            try:
                sock = self._connect()
                break
            except OSError as e:  # server may still be binding
                last = e
                time.sleep(retry_delay)
        else:
            raise ConnectionError(f"cannot reach {address}: {last}")
        with self._cv:
            self._install(sock)

    def _connect(self) -> socket.socket:
        host, port = self._address.rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=30)
        # blocking calls (barrier, ssp_wait) may legitimately park for longer
        # than any fixed socket timeout; request-level timeouts are carried in
        # the header and enforced server-side, the launcher is the backstop
        s.settimeout(None)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    def _install(self, sock: socket.socket) -> None:
        """Adopt a connected socket (caller holds ``_cv``): bump the
        connection generation and start the generation's reader and
        writer threads."""
        self._gen += 1
        self._bin_gen_ok = False  # codec re-negotiates per connection
        self._rseq_gen_ok = False  # until the peer proves it echoes seqs
        self._feat_gen_ok = False  # features re-negotiate per connection
        self._peer_features = frozenset()
        self._sock = sock
        threading.Thread(
            target=self._read_loop, args=(sock, self._gen), daemon=True,
            name="ps-rpc-reader",
        ).start()
        threading.Thread(
            target=self._write_loop, args=(sock, self._gen), daemon=True,
            name="ps-rpc-writer",
        ).start()

    # -- completion side --------------------------------------------------

    def _read_loop(self, sock: socket.socket, gen: int) -> None:
        reader = FrameReader(sock)  # this thread owns the receive side
        while True:
            try:
                rep, arrays, nbytes, was_bin = recv_frame_ex(reader)
            except (ConnectionError, OSError):
                break
            except (ValueError, KeyError, IndexError, struct.error,
                    zlib.error):
                # undecodable frame (corrupt stream or compressed chunk,
                # incompatible codec version): framing is lost — treat
                # the connection as dead so the heal reconnects and
                # resends the window, instead of stranding every pending
                # future forever
                break
            p: _PendingCall | None = None
            bin_ok = was_bin or bool(rep.pop("_bh", False))
            feat_ack = rep.pop("_feat", None)
            with self._cv:
                if self._closed or self._gen != gen:
                    return  # stale reader: a heal already replaced this conn
                if bin_ok and self._hdr_bin and not self._bin_gen_ok:
                    # the peer proved it decodes binary (replied binary,
                    # or acked our _bh advert): switch this connection
                    self._bin_gen_ok = True
                if feat_ack is not None and not self._feat_gen_ok:
                    # the peer named the features it supports: the
                    # connection may use exactly those from here on
                    self._peer_features = frozenset(feat_ack)
                    self._feat_gen_ok = True
                self.bytes_in += nbytes
                seq = rep.pop("_rseq", None)
                if seq is not None:
                    # the peer echoes sequence numbers: reply matching is
                    # order-independent, so the writer may prioritize
                    self._rseq_gen_ok = True
                    p = self._pending.pop(seq, None)  # None: dup of a resend
                elif self._pending:
                    # reply without an echo (legacy server): per-connection
                    # dispatch is serial and in order, the oldest wins
                    _, p = self._pending.popitem(last=False)
                self._cv.notify_all()  # window space freed
            if p is not None:
                self._complete(p, rep, arrays)
        self._conn_died(sock, gen)

    def _complete(self, p: _PendingCall, rep: dict[str, Any], arrays: Arrays) -> None:
        # client-observed latency: queueing + wire + service + any
        # transparent retries/reconnects this call absorbed
        dt = time.perf_counter() - p.t0
        tid = (p.header.get("_trace") or {}).get("tid")
        latency_histograms.observe(f"client.{p.cmd}", dt, exemplar=tid)
        # latency forensics (ISSUE 15): the reply's server-timing echo
        # splits this call's wall time into wire vs server vs apply
        # segments; the slowest-K records ride the heartbeat piggyback
        # for `cli whylate --scheduler` / the `cli top` breakdown line
        slow_ops.observe(
            p.cmd, dt,
            svc_us=rep.get("_svc_us"),
            apw_us=rep.get("_apw_us"),
            apl_us=rep.get("_apl_us"),
            tid=tid,
        )
        self._completed_n += 1  # GIL-atomic; feeds the stall probe
        flightrec.record(
            "rpc.reply", cmd=p.cmd, cid=self._cid, seq=p.seq,
            ok=rep.get("ok", True),
        )
        if self._adaptive:
            self._lat_hist.observe(dt)
            self._adapt_n += 1
            if self._adapt_n >= self._ADAPT_EVERY:
                self._adapt_n = 0
                self._maybe_adapt()
        if not rep.get("ok", True):
            p.future.set_exception(
                RuntimeError(f"{p.cmd} failed remotely: {rep.get('error')}")
            )
        else:
            p.future.set_result((rep, arrays))

    def _maybe_adapt(self) -> None:
        """Adaptive window policy over the last ``_ADAPT_EVERY``
        completions' latency-histogram DELTA (the PR-2 log2 buckets —
        exact under subtraction): a p99 blowup past 4x the p50 EMA halves
        the effective window (queueing delay is the symptom of a window
        the server can't drain); a healthy p99 while the window was
        actually saturated grows it back one step toward the ceiling."""
        snap = self._lat_hist.snapshot()
        last, self._adapt_last = self._adapt_last, snap
        if last is None:
            return
        delta = {
            "count": snap["count"] - last.get("count", 0),
            "buckets": {
                k: c - last.get("buckets", {}).get(k, 0)
                for k, c in snap.get("buckets", {}).items()
            },
        }
        if delta["count"] <= 0:
            return
        p50 = hist_percentile(delta, 0.5)
        p99 = hist_percentile(delta, 0.99)
        if self._ema_p50 == 0.0:
            self._ema_p50 = p50
        with self._cv:
            peak, self._adapt_peak = self._adapt_peak, 0
            if p99 > 4 * max(self._ema_p50, 1e-6) and self._eff_window > 1:
                self._eff_window = max(1, self._eff_window // 2)
                wire_counters.inc("wire_window_shrinks")
            elif (
                self._eff_window < self._window
                and p99 <= 2 * max(self._ema_p50, 1e-6)
                and peak >= self._eff_window
            ):
                self._eff_window += 1
                wire_counters.inc("wire_window_grows")
                self._cv.notify_all()  # a waiter may now fit the window
        self._ema_p50 = 0.8 * self._ema_p50 + 0.2 * p50

    @property
    def effective_window(self) -> int:
        """Current in-flight bound (== the configured window unless
        adaptive_window is shaping it)."""
        with self._cv:
            return self._eff_window

    @property
    def peer_features(self) -> frozenset[str]:
        """Features the CURRENT connection's peer acked (empty until the
        first ack, and after every reconnect until re-negotiated) —
        callers must treat an empty set as 'assume the baseline wire'."""
        with self._cv:
            return self._peer_features

    def _conn_died(self, sock: socket.socket, gen: int) -> None:
        """A connection failed under its reader (or a sender): tear it
        down and, when requests are stranded in flight, run the heal."""
        flightrec.record(
            "rpc.conn_died", addr=self._address, cid=self._cid, gen=gen,
        )
        heal = False
        with self._cv:
            if self._closed or self._gen != gen:
                return
            if self._sock is sock:
                try:
                    sock.close()
                except OSError:
                    pass
                self._sock = None
            if self._pending and not self._healing:
                self._healing = True
                heal = True
            self._cv.notify_all()
        if heal:
            self._heal()

    # -- healing ----------------------------------------------------------

    def _heal(self) -> None:
        """Reconnect and resend EVERY pending request under the same cid +
        sequence numbers (the server's reply cache turns the at-least-once
        resends into exactly-once applies, whole window included). Caller
        owns ``self._healing``. On an exhausted window every pending
        future fails with ConnectionError."""
        wire_counters.inc("rpc_retries")
        trace.instant("rpc.retry", cat="rpc", addr=self._address)
        if trace.enabled():
            # the heal usually runs on a reader/writer thread with no
            # live span: mark the retry on EVERY stranded call's OWN
            # trace (explicit ctx), so tail capture's anomaly gate
            # promotes the traces that actually absorbed this reconnect
            with self._cv:
                tctxs = [
                    p.header.get("_trace") for p in self._pending.values()
                ]
            for tctx in tctxs:
                if tctx:
                    trace.instant(
                        "rpc.retry", cat="rpc", ctx=tctx,
                        addr=self._address,
                    )
        flightrec.record(
            "rpc.heal.begin", addr=self._address, cid=self._cid,
        )
        deadline = time.monotonic() + self._reconnect_timeout_s
        attempt = 0
        while True:
            with self._cv:
                closed = self._closed
                # futures that opted out of retrying die with the conn
                doomed = (
                    [] if closed
                    else [p for p in self._pending.values() if not p.retry]
                )
                for p in doomed:
                    del self._pending[p.seq]
            if closed:
                self._abort_heal(
                    ConnectionError(f"client to {self._address} is closed")
                )
                return
            for p in doomed:
                p.future.set_exception(
                    ConnectionError(f"connection to {self._address} lost")
                )
            try:
                sock = self._connect()
            except OSError as e:
                if time.monotonic() >= deadline:
                    self._abort_heal(ConnectionError(
                        f"server {self._address} unreachable for "
                        f"{self._reconnect_timeout_s}s: {e}"
                    ))
                    return
                # exponential backoff + jitter: a server resetting every
                # connect must not be hammered at full speed, and lockstep
                # clients must not reconnect in synchronized waves
                delay = min(0.05 * (1 << min(attempt, 6)), 2.0)
                delay *= 0.5 + self._rng.random()
                time.sleep(min(delay, max(deadline - time.monotonic(), 0.0)))
                attempt += 1
                continue
            with self._cv:
                closed = self._closed
                if not closed:
                    self._install(sock)
                    pend = list(self._pending.values())
            if closed:
                try:
                    sock.close()
                except OSError:
                    pass
                self._abort_heal(
                    ConnectionError(f"client to {self._address} is closed")
                )
                return
            wire_counters.inc("rpc_reconnects")
            trace.instant("rpc.reconnect", cat="rpc", addr=self._address)
            try:
                # one coalesced gather: the whole stranded window resends
                # in a single write, same seqs (dedup makes it exactly-once)
                bufs: list = []
                total = 0
                for p in pend:
                    fb, n = build_frame(p.header, p.arrays)
                    bufs.extend(fb)
                    total += n
                if bufs:
                    _send_gather(sock, bufs)
                with self._cv:
                    self.bytes_out += total
                    for p in pend:
                        p.sent = True
            except (ConnectionError, OSError):
                # the replacement died mid-resend: drop it and retry
                # within the same window (its reader sees a stale gen
                # after the next install, or tears the sock down first)
                with self._cv:
                    if self._sock is sock:
                        try:
                            sock.close()
                        except OSError:
                            pass
                        self._sock = None
                if time.monotonic() >= deadline:
                    self._abort_heal(ConnectionError(
                        f"server {self._address} kept resetting for "
                        f"{self._reconnect_timeout_s}s"
                    ))
                    return
                continue
            with self._cv:
                # the resend "succeeded" locally (bytes in the kernel
                # buffer), but the replacement may ALREADY be dead: its
                # reader, seeing EOF while _healing was still True,
                # deferred to this heal (see _conn_died) and nulled the
                # socket. Declaring victory then would strand the whole
                # window — sent-claimed pending entries with no socket,
                # no writer and no healer (a real livelock caught by the
                # chaos drills under load). Only a still-installed
                # socket ends the heal; otherwise retry in-window.
                healed = self._sock is sock
                if healed:
                    self._healing = False
                    self._cv.notify_all()
            if not healed:
                if time.monotonic() >= deadline:
                    self._abort_heal(ConnectionError(
                        f"server {self._address} kept resetting for "
                        f"{self._reconnect_timeout_s}s"
                    ))
                    return
                continue
            flightrec.record(
                "rpc.healed", addr=self._address, cid=self._cid,
                resent=len(pend),
            )
            return

    def _abort_heal(self, exc: Exception) -> None:
        """Fail every pending future and release the heal. Futures complete
        OUTSIDE the lock: a done-callback may issue a follow-up call on
        this client, and ``_cv`` is not reentrant."""
        flightrec.record(
            "rpc.heal.failed", addr=self._address, cid=self._cid,
        )
        with self._cv:
            failed = list(self._pending.values())
            self._pending.clear()
            self._healing = False
            self._cv.notify_all()
        for p in failed:
            if not p.future.done():
                p.future.set_exception(exc)

    # -- issue side -------------------------------------------------------

    def call_async(
        self, cmd: str, arrays: Arrays | None = None, *, _retry: bool = True,
        _seq: int | str | None = None, _urgent: bool = False,
        _inline: bool = False, **fields: Any,
    ) -> Future:
        """Issue one request without waiting for its reply; returns a
        Future of ``(reply_header, reply_arrays)`` (failed remotely =>
        RuntimeError, connection exhausted => ConnectionError).

        ``_seq`` overrides the auto-allocated sequence number: a caller
        that re-issues a logical request across *rebuilt* clients (e.g.
        ``ServerHandle._keyed_call``) passes the same value each time so
        every delivery is one dedup identity. Caller-owned seqs must live
        in a disjoint namespace (the handle uses ``"k<n>"`` strings) so
        they can never collide with the internal integer counter.

        ``_urgent`` bypasses the window bound — ONLY for re-issues of an
        already-admitted logical call (the need_keys bounce), which may
        run on the reader thread and must never block on window space
        that same thread is responsible for freeing."""
        with trace.span(f"rpc.{cmd}", cat="rpc", addr=self._address):
            # propagate this span's identity in the header so the server's
            # dispatch span joins the same trace
            ctx = trace.wire_context()
            with self._cv:
                if not _urgent:
                    self._cv.wait_for(
                        lambda: self._closed
                        or len(self._pending) < self._eff_window
                    )
                if self._closed:
                    raise ConnectionError(
                        f"client to {self._address} is closed"
                    )
                if _seq is None:
                    _seq = self._next_seq
                    self._next_seq += 1
                header = {"cmd": cmd, "_cid": self._cid, "_seq": _seq, **fields}
                if self._hdr_bin and not self._bin_gen_ok:
                    # codec advert: ask the peer to confirm binary headers
                    # (ignored by old servers, acked by new ones)
                    header["_bh"] = 1
                if self._features and not self._feat_gen_ok:
                    # feature advert (see __init__): repeats until the
                    # first ack; old servers leave it in the header,
                    # where every handler ignores it
                    header["_feat"] = sorted(self._features)
                if ctx is not None:
                    header["_trace"] = ctx
                p = _PendingCall(_seq, cmd, header, arrays, _retry)
                self._pending[_seq] = p
                flightrec.record(
                    "rpc.issue", cmd=cmd, cid=self._cid, seq=_seq,
                )
                if len(self._pending) > self._adapt_peak:
                    self._adapt_peak = len(self._pending)
                wire_counters.observe_max(
                    "rpc_inflight_peak", len(self._pending)
                )
                sock, gen = self._sock, self._gen
                # fast path for LATENCY-bound callers (sync `call`): no
                # unsent backlog and a live conn — claim and send inline,
                # skipping the writer-thread handoff a lockstep caller
                # would only pay latency for. THROUGHPUT-bound async
                # callers skip it: their frames queue for the writer,
                # whose batches coalesce into single gather writes (and
                # arrive at the server as bursts its reply coalescing
                # batches right back).
                inline = (
                    _inline
                    and sock is not None
                    and not self._healing
                    and not any(
                        q is not p and not q.sent and not q.future.done()
                        for q in self._pending.values()
                    )
                )
                use_bin = self._hdr_bin and self._bin_gen_ok
                if inline:
                    p.sent = True
                else:
                    self._cv.notify_all()  # wake the connection's writer
            if inline:
                bufs, n = build_frame(p.header, p.arrays, bin_hdr=use_bin)
                try:
                    with self._send_lock:
                        # psl: ignore[blocking-under-lock]: _send_lock exists solely to serialize writes to one socket between this inline fast path and the writer thread; it guards no other state and the reader thread never takes it
                        _send_gather(sock, bufs)
                    with self._cv:
                        self.bytes_out += n
                except (ConnectionError, OSError):
                    self._conn_died(sock, gen)  # heal resends the claim
            else:
                self._pump(p)
        return p.future

    def _pump(self, p: _PendingCall) -> None:
        """After registering ``p``: make sure a connection exists for the
        writer thread to carry it, healing (or failing fast for no-retry
        callers) when the wire is down."""
        while True:
            with self._cv:
                if p.future.done() or p.sent:
                    return
                if self._healing:
                    self._cv.wait()  # the healer resends p for us
                    continue
                if self._sock is not None:
                    return  # the connection's writer thread owns the send
                if self._closed or not p.retry:
                    self._pending.pop(p.seq, None)
                    self._cv.notify_all()
                    raise ConnectionError(
                        f"client to {self._address} is "
                        + ("closed" if self._closed else "disconnected")
                    )
                # connection down and nobody healing: this caller becomes
                # the healer (fresh retry window)
                self._healing = True
            self._heal()

    def _write_loop(self, sock: socket.socket, gen: int) -> None:
        """The connection's writer: drain every unsent pending frame,
        COALESCING each batch into one gather write. While a sendmsg
        blocks on backpressure, new requests pile up in pending — so with
        syscall-priced hosts and small frames a full window rides ONE
        syscall, and the peer's FrameReader often picks the burst up in
        one recv. Claims (``sent``) happen under the lock BEFORE the
        write: a died connection hands everything to the heal, which
        resends the whole pending map regardless of claims."""
        while True:
            with self._cv:
                while True:
                    if self._closed or self._gen != gen or self._sock is not sock:
                        return
                    if not self._healing:
                        batch = [
                            q for q in self._pending.values()
                            if not q.sent and not q.future.done()
                        ]
                        if batch:
                            break
                    self._cv.wait()
                for q in batch:
                    q.sent = True  # claimed; heal ignores claims on resend
                use_bin = self._hdr_bin and self._bin_gen_ok
                prio_ok = self._rseq_gen_ok
            # two-lane writer: control frames (heartbeat, ssp clock,
            # workload fetch) lead the coalesced gather so they never
            # queue behind a multi-MiB push sharing this connection
            # (stable sort: FIFO preserved within each lane). ONLY once
            # the peer has echoed an _rseq: a legacy no-echo server is
            # matched by reply ORDER, which reordering would corrupt.
            if prio_ok:
                batch.sort(key=lambda q: q.cmd not in _PRIO_CMDS)
            bufs: list = []
            total = 0
            for q in batch:
                fb, n = build_frame(q.header, q.arrays, bin_hdr=use_bin)
                bufs.extend(fb)
                total += n
            if len(batch) > 1:
                wire_counters.inc("wire_frames_coalesced", len(batch) - 1)
            try:
                with self._send_lock:
                    # psl: ignore[blocking-under-lock]: _send_lock exists solely to serialize socket writes between the writer thread and the inline fast path; a send parked on backpressure is the socket's own flow control, not contended state
                    _send_gather(sock, bufs)
            except (ConnectionError, OSError):
                self._conn_died(sock, gen)  # heal resends the claimed batch
                return
            with self._cv:
                self.bytes_out += total

    def call(
        self, cmd: str, arrays: Arrays | None = None, *, _retry: bool = True,
        _seq: int | str | None = None, **fields: Any,
    ) -> tuple[dict[str, Any], Arrays]:
        """Synchronous round trip: ``call_async(...).result()`` on the
        latency fast path. Concurrent callers pipeline on the shared
        window instead of serializing."""
        fut = self.call_async(
            cmd, arrays, _retry=_retry, _seq=_seq, _inline=True, **fields
        )
        return fut.result()

    def stall_probe(self) -> tuple[bool, int]:
        """Watchdog probe for data-plane clients (pull/push pipelines,
        where no command legitimately parks): busy while requests are in
        flight and no heal owns them; progress is matched completions —
        a reader thread parked past every deadline is in-flight work
        with no completions moving. Control clients (barrier/ssp_wait
        park by design) must NOT be registered on this."""
        with self._cv:
            return (
                bool(self._pending) and not self._healing and not self._closed,
                self._completed_n,
            )

    @property
    def identity(self) -> tuple[str, int]:
        """(cid, next unused internal seq) — transfer into a replacement
        client (``RpcClient(..., cid=, start_seq=)``) so the server's
        dedup state keeps recognizing the logical caller across rebuilds."""
        with self._cv:
            return self._cid, self._next_seq

    def close(self) -> None:
        with self._cv:
            self._closed = True  # no reconnects on behalf of a closed client
            sock, self._sock = self._sock, None
            failed = list(self._pending.values())
            self._pending.clear()
            self._cv.notify_all()
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        for p in failed:
            if not p.future.done():
                p.future.set_exception(
                    ConnectionError(f"client to {self._address} is closed")
                )


class Coordinator:
    """The scheduler endpoint (ref: Postoffice on the scheduler node).

    Owns: node registry, named barriers, a blob KV (small host arrays),
    the workload pool, merged progress, heartbeats, and the SSP clock.
    All commands are served by ``RpcServer`` threads; blocking commands
    (barrier / blocking kv_get / ssp_wait) park the connection's thread.

    Self-healing control plane: ``start_recovery`` runs a sweep thread that
    promotes ``HeartbeatMonitor.dead()`` into ``WorkloadPool.
    reassign_worker`` + SSP-clock release, so a dead worker's tasks drain
    onto survivors without any scheduler-side polling logic (ref: the
    scheduler's dead-node handling driving recovery).
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        heartbeat_timeout_s: float = 30.0,
        recovery_interval_s: float = 0.0,
        fault_plan: FaultPlan | None = None,
        slo_cfg: "SloConfig | None" = None,
        series_capacity: int = 360,
        series_window_s: float = 60.0,
        audit_cfg: "AuditConfig | None" = None,
    ):
        from parameter_server_tpu.utils.auditor import Auditor
        from parameter_server_tpu.utils.config import SloConfig
        from parameter_server_tpu.utils.slo import SloEngine, parse_rules

        self._nodes: dict[int, dict[str, Any]] = {}
        self._next_id = 0
        self._barriers: dict[str, list[int]] = {}  # name -> [arrived, generation]
        self._kv: dict[str, tuple[dict, Arrays]] = {}
        self._pool: WorkloadPool | None = None
        self._progress: dict[int, dict[str, Any]] = {}
        self._monitor = HeartbeatMonitor(
            heartbeat_timeout_s, series_capacity=series_capacity
        )
        # the live-ops plane (ISSUE 13): per-node telemetry history is
        # retained by the monitor; this engine turns it into multi-window
        # burn-rate alerts, evaluated on every recovery sweep (alerts
        # fire with no viewer attached) and on every telemetry query
        scfg = slo_cfg or SloConfig()
        self._slo = SloEngine(
            parse_rules(scfg.rules),
            short_window_s=scfg.short_window_s,
            long_window_s=scfg.long_window_s,
        )
        self._series_window_s = series_window_s
        # the scheduler process never heartbeats to itself, but it OWNS
        # cluster-level signals (the SSP clock's ssp_blocked_ms, control
        # dedup/recovery counters) — without its own ring the shipped
        # ssp_blocked_ms SLO rule could never see data. Fed by
        # _observe_self() on every sweep/telemetry pass, rate-limited so
        # a polling dashboard can't flood it with sub-second entries.
        from parameter_server_tpu.utils.timeseries import TimeSeriesRing

        self._self_ring = TimeSeriesRing(series_capacity)
        self._self_last = 0.0
        # the live audit plane (ISSUE 14): heartbeat-piggybacked event
        # batches from every node stream through the shared protocol
        # monitors here; the coordinator's OWN spooled events (SSP clock
        # movements, its rpc traffic) are drained inline each sweep as
        # the "coord" stream, the way _self_ring covers its telemetry
        self._auditor = Auditor(audit_cfg)
        self._clock: SSPClock | None = None
        self._cv = threading.Condition()
        # batched beat/progress ingestion (ROADMAP carry-over): these
        # commands arrive from EVERY node at heartbeat cadence, and
        # taking _cv (or the monitor lock) once per frame made the
        # coordinator's hottest traffic its most lock-contended. Frames
        # now land in this deque (GIL-atomic append, no lock) and ONE
        # serving thread at a time drains EVERYTHING queued under a
        # single _cv acquire + a single monitor-lock acquire
        # (beat_many); concurrent ingest threads skip the drain instead
        # of queueing on the lock — their frames ride the owner's loop.
        # Safe because beats and progress are last-writer-wins
        # telemetry; readers (dead/telemetry/progress_merged/sweep)
        # drain with wait=True first, so every frame acked before a read
        # is visible to it.
        self._ingest: deque[tuple[str, int, Any]] = deque()
        self._ingest_lock = threading.Lock()  # one drainer at a time
        self._recovered: dict[int, dict[str, Any]] = {}  # worker rank -> info
        self._sweep_stop = threading.Event()
        self._sweep_thread: threading.Thread | None = None
        self.server = RpcServer(
            self._handle, host, port, fault_plan=fault_plan,
            # reads and last-writer-wins/monotonic writes: re-applying a
            # resend is harmless, and kv_get replies can carry model-sized
            # blobs that must not be pinned in the reply cache
            idempotent_cmds=frozenset({
                "kv_get", "kv_set", "nodes", "beat", "progress",
                "progress_merged", "workload_stats", "ssp_progress",
                "telemetry", "audit",
            }),
            blocking_cmds=frozenset({"barrier", "ssp_wait", "kv_get"}),
        )
        self.server.start()
        self.address = self.server.address
        if recovery_interval_s > 0:
            self.start_recovery(recovery_interval_s)

    # -- recovery sweep --------------------------------------------------

    def start_recovery(self, interval_s: float = 0.5) -> None:
        """Arm the dead-node sweep (idempotent): every ``interval_s`` the
        monitor's overdue workers have their workloads requeued and their
        SSP clock retired, so surviving workers drain their tasks."""
        if self._sweep_thread is not None:
            return
        def sweep() -> None:
            while not self._sweep_stop.wait(interval_s):
                self._sweep_once()
        self._sweep_thread = threading.Thread(target=sweep, daemon=True)
        self._sweep_thread.start()

    def _observe_self(self) -> None:
        """Roll the coordinator's own telemetry into its ring (at most
        ~4x/second however often sweeps and dashboards ask)."""
        now = time.time()
        if now - self._self_last < 0.25:
            return
        self._self_last = now
        self._self_ring.observe(
            telemetry_snapshot(roll_peaks=False), ts=now
        )

    def _slo_rings(self) -> dict[Any, Any]:
        return {**self._monitor.node_series(), "coord": self._self_ring}

    def _audit_pass(self) -> None:
        """One audit-plane pass: drain this process's own event spool
        (when armed) into the auditor as the "coord" stream, then run
        the watermark flush so unpaired facts past their window become
        violations. Rides the sweep AND every audit/telemetry query —
        violations must fire with no viewer attached."""
        from parameter_server_tpu.utils import flightrec

        sp = flightrec.audit_spool()
        if sp is not None:
            batches = sp.drain(max_batches=16)
            if batches:
                self._auditor.ingest("coord", batches, role="coordinator")
                sp.ack()  # no wire between drain and ingest: always lands
        self._auditor.flush()

    def _sweep_once(self) -> None:
        self._drain_ingest(wait=True)  # a queued beat must not read dead
        # SLO pass rides the sweep cadence: alerts must fire (and land in
        # the flight recorder) even when nobody is watching `cli top`.
        # Audit first: a violation bumped now is in the snapshot the
        # self-ring roll below hands the burn-rate engine.
        self._audit_pass()
        self._observe_self()
        self._slo.evaluate(self._slo_rings())
        for nid in self._monitor.dead():
            with self._cv:
                info = dict(self._nodes.get(nid, {}))
            if info.get("role") != "worker" or "rank" not in info:
                continue  # dead servers are the scheduler's call (grace /
                # checkpoint-restart policy lives there, not here)
            rank = int(info["rank"])
            with self._cv:
                finished = f"worker_done/{rank}" in self._kv
            if finished:
                # clean completion: drop the corpse so dead() stays the
                # actionable list
                self._monitor.forget(nid)
                continue
            # no handled-before guard: forget(nid) below keeps a handled
            # death out of dead(), and a forgotten node only reappears
            # through a fresh beat — i.e. it was ALIVE again (restarted
            # rank or falsely-declared-dead straggler) and may hold fresh
            # workloads, so its next death must be recovered again too.
            # A second recovery of a rank overwrites its report entry.
            requeued = self._pool.reassign_worker(rank) if self._pool else []
            if self._clock is not None:
                self._clock.retire(rank)
            with self._cv:
                self._recovered[rank] = {"node_id": nid, "requeued": requeued}
                self._cv.notify_all()
            self._monitor.forget(nid)
            wire_counters.inc("workers_recovered")
            flightrec.record(
                "coord.dead_worker", rank=rank, node=nid,
                requeued=len(requeued),
            )

    # -- dispatch --------------------------------------------------------

    def _handle(
        self, header: dict[str, Any], arrays: Arrays
    ) -> tuple[dict[str, Any], Arrays]:
        cmd = header.pop("cmd")
        fn = getattr(self, f"_cmd_{cmd}", None)
        if fn is None:
            raise ValueError(f"unknown control command {cmd!r}")
        return fn(header, arrays)

    def _cmd_register(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        with self._cv:
            node_id = self._next_id
            self._next_id += 1
            self._nodes[node_id] = {"role": h.get("role", "?"), **h}
            self._cv.notify_all()
        return {"ok": True, "node_id": node_id}, {}

    def _cmd_nodes(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        with self._cv:
            # copy: serialization happens after the lock is released, and a
            # concurrent register mutating the live dict mid-dumps would
            # kill the connection thread
            return {"ok": True, "nodes": dict(self._nodes)}, {}

    def _cmd_barrier(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        """Block until ``count`` callers reach barrier ``name`` (ref:
        Postoffice::Barrier over node groups)."""
        name, count = h["name"], int(h["count"])
        with self._cv:
            st = self._barriers.setdefault(name, [0, 0])
            st[0] += 1
            if st[0] >= count:
                st[0] = 0
                st[1] += 1
                self._cv.notify_all()
                return {"ok": True}, {}
            gen = st[1]
            ok = self._cv.wait_for(
                lambda: self._barriers[name][1] > gen, timeout=h.get("timeout")
            )
            if not ok and self._barriers[name][1] == gen:
                st[0] -= 1  # withdraw our arrival: a later generation must
                # not release early on a participant that already gave up
        return {"ok": ok, "error": "barrier timeout" if not ok else None}, {}

    def _cmd_kv_set(self, h: dict, arrays: Arrays) -> tuple[dict, Arrays]:
        with self._cv:
            self._kv[h["key"]] = ({"fields": h.get("fields", {})}, arrays)
            self._cv.notify_all()
        return {"ok": True}, {}

    def _cmd_kv_get(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        key = h["key"]
        with self._cv:
            if h.get("block"):
                if not self._cv.wait_for(
                    lambda: key in self._kv, timeout=h.get("timeout")
                ):
                    return {"ok": False, "error": f"kv_get timeout on {key!r}"}, {}
            if key not in self._kv:
                return {"ok": True, "found": False}, {}
            meta, arrays = self._kv[key]
            return {"ok": True, "found": True, **meta}, arrays

    def _cmd_workload_init(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        with self._cv:
            if self._pool is None:
                self._pool = WorkloadPool(h["items"])
        return {"ok": True}, {}

    def _pool_or_raise(self) -> WorkloadPool:
        # explicit raise, not assert: must hold under ``python -O`` and
        # surface a clear remote error to a mis-ordered client
        if self._pool is None:
            raise RuntimeError("workload_init must be called first")
        return self._pool

    def _clock_or_raise(self) -> SSPClock:
        if self._clock is None:
            raise RuntimeError("ssp_init must be called first")
        return self._clock

    def _cmd_workload_fetch(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        pool = self._pool_or_raise()
        return {"ok": True, "workload": pool.fetch(int(h["worker"]))}, {}

    def _cmd_workload_finish(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        self._pool_or_raise().finish(h["workload"])
        return {"ok": True}, {}

    def _cmd_workload_stats(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        pool = self._pool_or_raise()
        return {"ok": True, "stats": pool.stats(), "all_done": pool.all_done}, {}

    def _cmd_workload_reassign(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        """Requeue workloads of a dead worker and/or stragglers by age
        (ref: WorkloadPool straggler/dead reassignment, driven by the
        scheduler's dead-node list)."""
        pool = self._pool_or_raise()
        requeued: list[str] = []
        if h.get("worker") is not None:
            requeued += pool.reassign_worker(int(h["worker"]))
        if h.get("older_than") is not None:
            requeued += pool.reassign_stragglers(float(h["older_than"]))
        return {"ok": True, "requeued": requeued}, {}

    def _cmd_progress(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        self._ingest.append(("progress", int(h["worker"]), h["record"]))
        self._drain_ingest()
        return {"ok": True}, {}

    def _cmd_progress_merged(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        self._drain_ingest(wait=True)  # every acked progress is merged
        with self._cv:
            reports = [dict(r) for r in self._progress.values()]
        return {"ok": True, "merged": merge_progress(reports)}, {}

    def _cmd_beat(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        self._ingest.append(("beat", int(h["node_id"]), h.get("stats")))
        self._drain_ingest()
        return {"ok": True}, {}

    def _drain_ingest(self, wait: bool = False) -> None:
        """Apply every queued beat/progress frame in batches: progress
        records land under ONE ``_cv`` acquire, beats under ONE monitor
        lock (``beat_many``) — however many frames the cluster managed
        to queue since the last drain. Ingest callers pass
        ``wait=False``: if another thread owns the drain, this frame
        rides that thread's loop instead of queueing a second acquire.
        Readers pass ``wait=True`` so they observe every frame whose
        reply has been (or is being) sent before they read."""
        if not self._ingest_lock.acquire(blocking=wait):
            return
        try:
            while True:
                batch: list[tuple[str, int, Any]] = []
                while True:
                    try:
                        batch.append(self._ingest.popleft())
                    except IndexError:
                        break
                if not batch:
                    return
                beats = [(k, v) for t, k, v in batch if t == "beat"]
                prog = [(k, v) for t, k, v in batch if t == "progress"]
                if prog:
                    with self._cv:
                        for worker, record in prog:
                            self._progress[worker] = record
                        self._cv.notify_all()
                # audit plane: peel each beat's piggybacked event batches
                # BEFORE the monitor retains the stats (latest_stats is a
                # telemetry view, not an event bus), then feed them after
                # the monitor lock is released — the auditor locks itself
                audit_feed: list[tuple[int, list]] = []
                for node_id, stats in beats:
                    if isinstance(stats, dict):
                        batches = stats.pop("audit", None)
                        if batches:
                            audit_feed.append((node_id, batches))
                if beats:
                    self._monitor.beat_many(beats)
                if audit_feed:
                    # role hints tighten hole-suppression targeting (a
                    # holed WORKER stream cannot hide a missing commit)
                    with self._cv:
                        roles = {
                            nid: self._nodes.get(nid, {}).get("role")
                            for nid, _ in audit_feed
                        }
                    for node_id, batches in audit_feed:
                        self._auditor.ingest(
                            node_id, batches, role=roles.get(node_id)
                        )
                if len(batch) > 1:
                    wire_counters.inc("coord_ingest_coalesced", len(batch) - 1)
                # loop: frames appended while we applied are ours too —
                # their ingest threads saw the held lock and moved on
        finally:
            self._ingest_lock.release()

    def _cmd_telemetry(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        """Cluster telemetry (ref: the scheduler's dashboard, reborn):
        every node's last heartbeat piggybacked a counters+histograms
        snapshot; this merges them — plus the coordinator's own process
        — into one cluster view, and returns the per-node detail."""
        self._drain_ingest(wait=True)  # acked beats are in latest_stats
        with self._cv:
            registry = {int(k): dict(v) for k, v in self._nodes.items()}
        per_node: dict[str, dict[str, Any]] = {}
        node_snaps: list[dict[str, Any]] = []
        for nid, stats in self._monitor.latest_stats().items():
            stats = dict(stats)
            tel = stats.pop("telemetry", None)
            info = registry.get(nid, {})
            per_node[str(nid)] = {
                "role": info.get("role", "?"),
                "rank": info.get("rank"),
                "stats": stats,
                "telemetry": tel,
            }
            if tel:
                node_snaps.append(tel)
        local = telemetry_snapshot()  # the coordinator's own process
        # the live-ops view (ISSUE 13): per-node windowed rates/p50/p99
        # from the retained beat history + the SLO engine's verdict
        # ("coord" is the scheduler process itself — SSP blocked time
        # and control-plane counters live only there)
        self._observe_self()
        window_s = float(h.get("window_s") or self._series_window_s)
        rings = self._slo_rings()
        series = {
            str(nid): ring.summary(window_s)
            for nid, ring in rings.items()
        }
        self._audit_pass()
        return {
            "ok": True,
            "nodes": per_node,
            "coordinator": local,
            "merged": merge_telemetry(node_snaps + [local]),
            "series": series,
            "slo": self._slo.evaluate(rings),
            "audit": self._auditor.summary(),
        }, {}

    def _cmd_audit(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        """The audit plane's read endpoint (``cli audit``): violation
        totals/panel + per-node stream accounting, after draining any
        queued beats (an acked batch is visible) and a watermark pass."""
        self._drain_ingest(wait=True)
        self._audit_pass()
        return {
            "ok": True,
            "audit": self._auditor.summary(
                recent=int(h.get("recent") or 20)
            ),
        }, {}

    def _cmd_dead(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        self._drain_ingest(wait=True)  # an acked beat must never read dead
        return {"ok": True, "dead": self._monitor.dead(), "alive": self._monitor.alive()}, {}

    def _cmd_recovered(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        """Worker ranks the recovery sweep has already handled (requeued +
        clock-retired); the scheduler merges these instead of running its
        own dead-worker logic."""
        with self._cv:
            return {
                "ok": True,
                "recovered": {str(r): dict(v) for r, v in self._recovered.items()},
            }, {}

    def _cmd_ssp_init(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        with self._cv:
            if self._clock is None:
                self._clock = SSPClock(int(h["num_workers"]), int(h["max_delay"]))
                # a wedged clock (workers parked, nothing finishing) is
                # one of the stalls the watchdog exists to catch
                watchdog.register(
                    f"ssp-clock:{id(self._clock)}",
                    self._clock.stall_probe,
                )
                # the audit plane's SSP monitor checks granted gate
                # passes against exactly this bound (dormant until told)
                self._auditor.set_ssp(
                    int(h["num_workers"]), int(h["max_delay"])
                )
        return {"ok": True}, {}

    def _cmd_ssp_wait(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        clock = self._clock_or_raise()
        ok = clock.wait(int(h["worker"]), int(h["step"]), h.get("timeout"))
        return {"ok": True, "granted": ok}, {}

    def _cmd_ssp_finish(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        self._clock_or_raise().finish(int(h["worker"]), int(h["step"]))
        return {"ok": True}, {}

    def _cmd_ssp_retire(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        self._clock_or_raise().retire(int(h["worker"]))
        return {"ok": True}, {}

    def _cmd_ssp_progress(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        return {"ok": True, **self._clock_or_raise().progress()}, {}

    def _cmd_shutdown(self, h: dict, _: Arrays) -> tuple[dict, Arrays]:
        raise RpcServer.Shutdown

    def stop(self) -> None:
        self._sweep_stop.set()
        if self._sweep_thread is not None:
            self._sweep_thread.join(timeout=5)
            self._sweep_thread = None
        with self._cv:
            if self._clock is not None:
                watchdog.unregister(f"ssp-clock:{id(self._clock)}")
        self.server.stop()


class ControlClient(RpcClient):
    """Typed convenience wrapper over the coordinator's commands."""

    def register(self, role: str, **fields: Any) -> int:
        rep, _ = self.call("register", role=role, **fields)
        return int(rep["node_id"])

    def barrier(self, name: str, count: int, timeout: float | None = None) -> None:
        rep, _ = self.call("barrier", name=name, count=count, timeout=timeout)
        if not rep["ok"]:  # pragma: no cover - timeout path
            raise TimeoutError(f"barrier {name!r} timed out")

    def kv_set(self, key: str, arrays: Arrays | None = None, **fields: Any) -> None:
        self.call("kv_set", arrays=arrays, key=key, fields=fields)

    def kv_get(
        self, key: str, block: bool = False, timeout: float | None = None
    ) -> tuple[dict[str, Any], Arrays] | None:
        rep, arrays = self.call("kv_get", key=key, block=block, timeout=timeout)
        if not rep.get("found"):
            return None
        return rep.get("fields", {}), arrays

    def workload_init(self, items: list[str]) -> None:
        self.call("workload_init", items=items)

    def workload_fetch(self, worker: int) -> str | None:
        rep, _ = self.call("workload_fetch", worker=worker)
        return rep["workload"]

    def workload_finish(self, workload: str) -> None:
        self.call("workload_finish", workload=workload)

    def workload_all_done(self) -> bool:
        rep, _ = self.call("workload_stats")
        return bool(rep["all_done"])

    def workload_stats(self) -> dict[str, int]:
        rep, _ = self.call("workload_stats")
        return rep["stats"]

    def workload_reassign(
        self, worker: int | None = None, older_than: float | None = None
    ) -> list[str]:
        rep, _ = self.call(
            "workload_reassign", worker=worker, older_than=older_than
        )
        return rep["requeued"]

    def nodes(self) -> dict[str, dict[str, Any]]:
        """Registry snapshot; keys are node-id strings (JSON wire)."""
        rep, _ = self.call("nodes")
        return rep["nodes"]

    def dead_nodes(self) -> tuple[list[int], list[int]]:
        rep, _ = self.call("dead")
        return rep["dead"], rep["alive"]

    def recovered_workers(self) -> dict[int, dict[str, Any]]:
        """Worker ranks the coordinator's recovery sweep has handled."""
        rep, _ = self.call("recovered")
        return {int(r): v for r, v in rep["recovered"].items()}

    def progress(self, worker: int, record: dict[str, Any]) -> None:
        self.call("progress", worker=worker, record=record)

    def progress_merged(self) -> dict[str, Any]:
        rep, _ = self.call("progress_merged")
        return rep["merged"]

    def beat(self, node_id: int, stats: dict | None = None) -> None:
        self.call("beat", node_id=node_id, stats=stats)

    def telemetry(self, window_s: float | None = None) -> dict[str, Any]:
        """Cluster telemetry: per-node snapshots + the merged view
        (counters summed, latency histograms merged bucket-wise), plus
        the live-ops blocks — per-node windowed ``series`` summaries
        over ``window_s`` (the coordinator's default when None) and the
        ``slo`` engine's health/alert verdict."""
        rep, _ = self.call("telemetry", window_s=window_s)
        return {
            k: rep[k]
            for k in (
                "nodes", "coordinator", "merged", "series", "slo", "audit",
            )
            if k in rep
        }

    def audit(self, recent: int = 20) -> dict[str, Any]:
        """The audit plane's summary: violation totals, recent panel,
        per-node stream accounting (``cli audit``'s feed)."""
        rep, _ = self.call("audit", recent=recent)
        return rep["audit"]

    def ssp_init(self, num_workers: int, max_delay: int) -> None:
        self.call("ssp_init", num_workers=num_workers, max_delay=max_delay)

    def ssp_wait(self, worker: int, step: int, timeout: float | None = None) -> bool:
        rep, _ = self.call("ssp_wait", worker=worker, step=step, timeout=timeout)
        return bool(rep["granted"])

    def ssp_finish(self, worker: int, step: int) -> None:
        self.call("ssp_finish", worker=worker, step=step)

    def ssp_retire(self, worker: int) -> None:
        self.call("ssp_retire", worker=worker)

    def shutdown_server(self) -> None:
        """Ask the remote RpcServer to stop (after acking)."""
        self.call("shutdown")
