"""Deterministic fault injection for the host control plane.

Reference analog: the OSDI'14 paper's fault-tolerance claims (vector-clock
idempotent retransmission, scheduler-driven recovery) are only credible if
every failure mode can be *produced on demand*. The reference exercised them
by killing processes under script/local.sh; this module goes further: a
seeded :class:`FaultPlan` armed on any ``RpcServer`` (and therefore any
``ShardServer`` or ``Coordinator``) perturbs the framed wire protocol itself
— dropping requests before they apply, severing connections after they
apply but before the reply lands, delaying frames, and duplicating frames —
so the retry/reconnect/dedup machinery in parallel/control.py is testable on
CPU with no real pod and no real packet loss.

Fault actions (decided per received frame, by command):

``drop``
    Discard the request *before* the handler runs and close the connection
    (the request was lost on the wire). Exercises pure resend.
``disconnect``
    Run the handler (side effects happen, the reply is cached by the dedup
    layer) then close the connection *without* replying (the reply was lost).
    Exercises reconnect + reply-cache dedup — the dangerous half of
    at-least-once delivery for non-idempotent commands.
``delay``
    Sleep ``delay_s`` before handling. Exercises stragglers, SSP waits and
    heartbeat-timeout tuning.
``duplicate``
    Deliver the frame to the dispatch layer twice (second reply discarded) —
    a duplicated frame in flight. Without dedup this double-applies.

Plans are deterministic given their seed: every probabilistic decision comes
from one ``random.Random(seed)`` stream (frame arrival order across
connection threads is still OS-scheduled, but a plan replayed over the same
frame sequence makes the same calls). ``shutdown`` frames are never
perturbed — chaos on the teardown handshake only tests the harness.

Arming: pass ``fault_plan=`` to ``RpcServer``/``ShardServer``/
``Coordinator``, or set the environment variables ``PS_FAULT_PLAN`` (spec
string) and ``PS_FAULT_SEED`` before the server process starts — the env
path is how ``launch_local`` and the multi-host test children arm every
node they spawn without new plumbing.

Spec DSL (``;``-separated rules; first token is the action, the rest
``key=value``)::

    drop,prob=0.05;delay,prob=0.1,delay_s=0.02;disconnect,cmd=push,every=7

Rule keys: ``cmd`` (exact command match, default ``*`` = any),
``prob`` (per-frame firing probability), ``every`` (fire on every Nth
matching frame instead of randomly), ``delay_s`` (for ``delay``),
``max`` (total firing budget for the rule; -1 = unbounded).
A JSON list of rule objects with the same keys (plus ``action``) is also
accepted (spec starting with ``[``).
"""

from __future__ import annotations

import json
import os
import random
import threading
from dataclasses import dataclass

ACTIONS = ("drop", "disconnect", "delay", "duplicate")

# commands chaos must never touch: perturbing the shutdown handshake only
# wedges the harness (a server that already stopped cannot be re-asked)
_EXEMPT_CMDS = frozenset({"shutdown"})

PLAN_ENV = "PS_FAULT_PLAN"
SEED_ENV = "PS_FAULT_SEED"


@dataclass
class FaultRule:
    """One perturbation rule; ``prob`` and ``every`` are alternatives
    (``every`` wins when > 0 — deterministic cadence beats dice)."""

    action: str
    cmd: str = "*"  # exact command match; "*" matches any
    prob: float = 0.0
    every: int = 0  # fire on every Nth matching frame (0 = use prob)
    delay_s: float = 0.02
    max_fires: int = -1  # firing budget; -1 unbounded
    seen: int = 0  # matching frames observed (mutated under plan lock)
    fires: int = 0

    def __post_init__(self) -> None:
        if self.action not in ACTIONS:
            raise ValueError(
                f"unknown fault action {self.action!r}; known: {ACTIONS}"
            )
        if self.every == 0 and not (0.0 <= self.prob <= 1.0):
            raise ValueError(f"prob must be in [0, 1], got {self.prob}")


@dataclass
class FaultDecision:
    action: str
    delay_s: float = 0.0


class FaultPlan:
    """Seeded, thread-safe decision engine consulted once per received
    frame. First matching rule that fires wins (rule order is priority)."""

    def __init__(self, rules: list[FaultRule], seed: int = 0):
        self._rules = list(rules)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.seed = seed
        self.frames = 0  # every frame this plan was consulted on

    def decide(self, cmd: str) -> FaultDecision | None:
        if cmd in _EXEMPT_CMDS:
            return None
        with self._lock:
            self.frames += 1
            for r in self._rules:
                if r.cmd != "*" and r.cmd != cmd:
                    continue
                r.seen += 1
                if r.max_fires >= 0 and r.fires >= r.max_fires:
                    continue
                fire = (
                    (r.seen % r.every == 0)
                    if r.every > 0
                    else (self._rng.random() < r.prob)
                )
                if not fire:
                    continue
                r.fires += 1
                from parameter_server_tpu.utils.metrics import wire_counters

                wire_counters.inc(f"fault_{r.action}")
                return FaultDecision(r.action, r.delay_s)
        return None

    def stats(self) -> dict[str, int]:
        """Per-action fire totals plus the consulted-frame count (the
        denominator for "≥ X% of frames were perturbed" assertions)."""
        with self._lock:
            out = {"frames": self.frames}
            for r in self._rules:
                out[r.action] = out.get(r.action, 0) + r.fires
            return out

    # -- construction ----------------------------------------------------

    @classmethod
    def parse(cls, spec: str, seed: int = 0) -> "FaultPlan":
        spec = spec.strip()
        if not spec:
            raise ValueError("empty fault-plan spec")
        if spec.startswith("["):
            rules = [cls._rule_from_dict(d) for d in json.loads(spec)]
            return cls(rules, seed=seed)
        rules = []
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            tokens = [t.strip() for t in part.split(",")]
            kw: dict = {"action": tokens[0]}
            for tok in tokens[1:]:
                if "=" not in tok:
                    raise ValueError(
                        f"bad fault-rule token {tok!r} in {part!r} "
                        "(expected key=value)"
                    )
                k, v = tok.split("=", 1)
                kw[k] = v
            rules.append(cls._rule_from_dict(kw))
        return cls(rules, seed=seed)

    @staticmethod
    def _rule_from_dict(d: dict) -> FaultRule:
        # the documented spelling is ``max`` in BOTH spec forms (DSL and
        # JSON); the dataclass field is max_fires
        d = {{"max": "max_fires"}.get(k, k): v for k, v in d.items()}
        known = {"action", "cmd", "prob", "every", "delay_s", "max_fires"}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"unknown fault-rule key(s) {sorted(unknown)}; known: "
                f"{sorted(known)}"
            )
        kw = dict(d)
        for k, cast in (
            ("prob", float), ("every", int), ("delay_s", float),
            ("max_fires", int),
        ):
            if k in kw:
                kw[k] = cast(kw[k])
        return FaultRule(**kw)

    @classmethod
    def from_env(cls, env: dict | None = None) -> "FaultPlan | None":
        """Build a plan from ``PS_FAULT_PLAN``/``PS_FAULT_SEED``; None when
        unset. Called by ``RpcServer`` at construction so every server in a
        spawned process tree arms itself from the launcher's environment."""
        env = os.environ if env is None else env
        spec = env.get(PLAN_ENV, "")
        if not spec:
            return None
        return cls.parse(spec, seed=int(env.get(SEED_ENV, "0")))
