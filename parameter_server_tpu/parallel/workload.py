"""Scheduler-side workload (file shard) assignment.

Reference analog: src/learner/workload_pool.h — the scheduler hands data
file shards to workers on demand, tracks completion, and can reassign a
shard whose worker died or straggles."""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


@dataclass
class _Assignment:
    workload: str
    worker: int
    t_assigned: float = field(default_factory=time.monotonic)


class WorkloadPool:
    """Thread-safe pool of named workloads (file shards)."""

    def __init__(self, workloads: list[str]):
        self._pending: list[str] = list(workloads)
        self._active: dict[str, _Assignment] = {}
        self._done: set[str] = set()
        self._attempts: dict[str, int] = {}  # workload -> times handed out
        self._reassigned = 0
        self._lock = threading.Lock()

    def fetch(self, worker: int) -> str | None:
        """Next workload for ``worker``; None when nothing is pending.
        Pop and assignment are one atomic step under the lock: two workers
        racing for a reassigned workload can never both become its owner
        (``_active`` is keyed by workload — one assignment at a time)."""
        with self._lock:
            if not self._pending:
                return None
            w = self._pending.pop(0)
            self._active[w] = _Assignment(w, worker)
            self._attempts[w] = self._attempts.get(w, 0) + 1
            return w

    def finish(self, workload: str) -> None:
        """Mark complete. A finish from a slow-but-alive worker whose shard
        was already requeued by reassign_stragglers still counts: the work
        is done, so drop it from pending instead of redoing it."""
        with self._lock:
            a = self._active.pop(workload, None)
            if a is None:
                if workload in self._pending:
                    self._pending.remove(workload)
                elif workload not in self._done:
                    raise KeyError(f"unknown workload {workload!r}")
            self._done.add(workload)

    def reassign_stragglers(self, older_than_s: float) -> list[str]:
        """Requeue workloads assigned longer than ``older_than_s`` ago
        (ref: straggler / dead-worker reassignment). Requeued work goes to
        the FRONT of the queue: recovery drains the stranded tasks before
        untouched pending ones."""
        now = time.monotonic()
        requeued = []
        with self._lock:
            for w, a in list(self._active.items()):
                if now - a.t_assigned > older_than_s:
                    del self._active[w]
                    requeued.append(w)
            self._pending[:0] = requeued
            self._reassigned += len(requeued)
        return requeued

    def reassign_worker(self, worker: int) -> list[str]:
        """Requeue everything held by a dead worker (front of the queue,
        like reassign_stragglers)."""
        requeued = []
        with self._lock:
            for w, a in list(self._active.items()):
                if a.worker == worker:
                    del self._active[w]
                    requeued.append(w)
            self._pending[:0] = requeued
            self._reassigned += len(requeued)
        return requeued

    def owner_of(self, workload: str) -> int | None:
        """Current owner rank, or None when not active (observability +
        the reassign-race tests' single-owner assertion)."""
        with self._lock:
            a = self._active.get(workload)
            return None if a is None else a.worker

    def attempts(self, workload: str) -> int:
        """How many times ``workload`` has been handed out (1 = never
        reassigned)."""
        with self._lock:
            return self._attempts.get(workload, 0)

    @property
    def all_done(self) -> bool:
        with self._lock:
            return not self._pending and not self._active

    @property
    def reassigned_total(self) -> int:
        with self._lock:
            return self._reassigned

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {
                "pending": len(self._pending),
                "active": len(self._active),
                "done": len(self._done),
                # exactly-once ledger: every hand-out either completed or
                # was requeued, so attempts == done + reassigned at the end
                # of a healthy run — a double-applied (non-deduped) fetch
                # breaks this invariant visibly
                "attempts": sum(self._attempts.values()),
                "reassigned": self._reassigned,
            }
