"""Scheduler-side workload (file shard) assignment.

Reference analog: src/learner/workload_pool.h — the scheduler hands data
file shards to workers on demand, tracks completion, and can reassign a
shard whose worker died or straggles."""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


@dataclass
class _Assignment:
    workload: str
    worker: int
    t_assigned: float = field(default_factory=time.monotonic)


class WorkloadPool:
    """Thread-safe pool of named workloads (file shards)."""

    def __init__(self, workloads: list[str]):
        self._pending: list[str] = list(workloads)
        self._active: dict[str, _Assignment] = {}
        self._done: set[str] = set()
        self._lock = threading.Lock()

    def fetch(self, worker: int) -> str | None:
        """Next workload for ``worker``; None when nothing is pending."""
        with self._lock:
            if not self._pending:
                return None
            w = self._pending.pop(0)
            self._active[w] = _Assignment(w, worker)
            return w

    def finish(self, workload: str) -> None:
        """Mark complete. A finish from a slow-but-alive worker whose shard
        was already requeued by reassign_stragglers still counts: the work
        is done, so drop it from pending instead of redoing it."""
        with self._lock:
            a = self._active.pop(workload, None)
            if a is None:
                if workload in self._pending:
                    self._pending.remove(workload)
                elif workload not in self._done:
                    raise KeyError(f"unknown workload {workload!r}")
            self._done.add(workload)

    def reassign_stragglers(self, older_than_s: float) -> list[str]:
        """Requeue workloads assigned longer than ``older_than_s`` ago
        (ref: straggler / dead-worker reassignment)."""
        now = time.monotonic()
        requeued = []
        with self._lock:
            for w, a in list(self._active.items()):
                if now - a.t_assigned > older_than_s:
                    del self._active[w]
                    self._pending.append(w)
                    requeued.append(w)
        return requeued

    def reassign_worker(self, worker: int) -> list[str]:
        """Requeue everything held by a dead worker."""
        requeued = []
        with self._lock:
            for w, a in list(self._active.items()):
                if a.worker == worker:
                    del self._active[w]
                    self._pending.append(w)
                    requeued.append(w)
        return requeued

    @property
    def all_done(self) -> bool:
        with self._lock:
            return not self._pending and not self._active

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {
                "pending": len(self._pending),
                "active": len(self._active),
                "done": len(self._done),
            }
