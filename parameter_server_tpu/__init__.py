"""parameter_server_tpu — a TPU-native parameter-server framework.

A from-scratch re-expression of the OSDI'14-generation C++ parameter server
(reference: ziyue1987/parameter_server — scheduler/server/worker processes
over ZeroMQ with Push/Pull on a range-sharded sparse key->value model) as an
idiomatic JAX/XLA/Pallas framework for TPU pods:

- "Servers" are HBM-resident parameter+optimizer slices, range-sharded over a
  ``jax.sharding.Mesh`` axis (GSPMD), not processes (ref: src/system/,
  src/parameter/ in the reference tree).
- ``Push``/``Pull`` lower to XLA collectives (reduce-scatter / all-gather or
  masked-gather + psum) under ``shard_map`` on ICI, not ZeroMQ point-to-point
  (ref: src/system/van.*, src/parameter/shared_parameter.h).
- Server-side updaters (SGD / AdaGrad / FTRL-proximal) are fused XLA / Pallas
  kernels over the sharded state (ref: src/app/linear_method/async_sgd.h
  server entries).
- The SSP bounded-delay clock survives as a host-side gate on step dispatch
  (ref: src/system/executor.* wait_time dependency tracking).

Package layout:
    utils/      config, hashing, key ranges, metrics, logging   (ref src/util/)
    kv/         the sharded KV store: pull/push/updaters        (ref src/parameter/)
    ops/        device kernels: segment ops, CSR matvec, Pallas (ref hot loops)
    parallel/   mesh construction, SSP clock, workload pool     (ref src/system/)
    data/       parsers, localizer, minibatch readers           (ref src/data/)
    models/     apps: linear_method, MF, word2vec, wide&deep    (ref src/app/)
    filters/    bandwidth codecs for DCN paths                  (ref src/filter/)
"""

__version__ = "0.1.0"

from parameter_server_tpu.utils.keyrange import KeyRange  # noqa: F401
