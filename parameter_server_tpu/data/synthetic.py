"""Synthetic sparse classification data for tests and benchmarks.

Stands in for the reference's demo dataset (RCV1 under guide/) since this
environment has no network: a sparse logistic ground-truth model generates
separable-but-noisy data with a long-tailed feature distribution, matching
the shape of CTR data (few hot features, many rare)."""

from __future__ import annotations

import numpy as np


def make_sparse_logistic(
    num_examples: int,
    num_features: int,
    nnz_per_example: int = 32,
    noise: float = 0.5,
    seed: int = 0,
    zipf_a: float = 1.3,
):
    """Returns (labels, keys, values, true_w). Feature ids follow a Zipf
    law so batches have realistic hot/cold key overlap."""
    rng = np.random.default_rng(seed)
    true_w = (rng.normal(size=num_features) * (rng.random(num_features) < 0.2)).astype(
        np.float32
    )
    labels = np.empty(num_examples, dtype=np.float32)
    keys: list[np.ndarray] = []
    values: list[np.ndarray] = []
    for i in range(num_examples):
        n = max(1, int(rng.poisson(nnz_per_example)))
        k = np.minimum(rng.zipf(zipf_a, size=n) - 1, num_features - 1).astype(
            np.uint64
        )
        k = np.unique(k)
        v = rng.normal(loc=1.0, scale=0.3, size=len(k)).astype(np.float32)
        margin = float(v @ true_w[k.astype(np.int64)]) + noise * rng.normal()
        labels[i] = 1.0 if margin > 0 else 0.0
        keys.append(k)
        values.append(v)
    return labels, keys, values, true_w


def write_libsvm(path, labels, keys, values) -> None:
    """Dump rows in libsvm text format (for parser round-trip tests)."""
    with open(path, "w") as f:
        for y, k, v in zip(labels, keys, values):
            feats = " ".join(f"{int(ki)}:{vi:.6g}" for ki, vi in zip(k, v))
            f.write(f"{int(y)} {feats}\n")


def make_criteo_ctr(
    num_examples: int,
    cat_vocab: int = 64,
    informative: int = 4,
    seed: int = 0,
):
    """Synthetic Criteo-shaped CTR data: 13 integer columns (noise here)
    and 26 categorical columns, the first ``informative`` of which carry
    the label signal. Returns (labels, ints (N, 13), cats (N, 26))."""
    rng = np.random.default_rng(seed)
    ints = rng.integers(0, 100, size=(num_examples, 13))
    cats = rng.integers(0, cat_vocab, size=(num_examples, 26))
    w = rng.normal(size=(informative, cat_vocab)) * 2.0
    logits = sum(w[j, cats[:, j]] for j in range(informative))
    labels = (rng.random(num_examples) < 1.0 / (1.0 + np.exp(-logits))).astype(
        np.float32
    )
    return labels, ints, cats


def write_criteo(path, labels, ints, cats) -> None:
    """Dump rows in Criteo TSV format: label, 13 ints, 26 hex categorical
    ids (the reference's flagship CTR input format)."""
    with open(path, "w") as f:
        for y, ii, cc in zip(labels, ints, cats):
            cols = (
                [str(int(y))]
                + [str(int(v)) for v in ii]
                + [format(int(v), "x") for v in cc]
            )
            f.write("\t".join(cols) + "\n")
