"""ctypes bindings for the native (C++) text parsers.

Reference analog: src/data/text_parser.cc — the reference's parsing is
C++; this keeps the rebuild's ingest hot path native too. The extension is
built on demand with ``make`` (g++); if unavailable, callers fall back to
the Python parsers in data/libsvm.py, which produce identical rows.

Chunked protocol: files are read in ~8 MiB chunks cut at line boundaries;
each chunk is parsed in one C call into flat CSR arrays (labels,
row_splits, keys, vals, slots)."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from collections.abc import Iterator
from pathlib import Path

import numpy as np

_NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"
_LIB_ENV = "PS_TPU_NATIVE_LIB"

FlatRows = tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, "np.ndarray | None"]
# (labels (R,), row_splits (R+1,), keys (N,), vals (N,), slots (N,) or
#  None for SLOTLESS_FORMATS — all slot ids are 0 there)

# Formats with a native fast path; the single source of truth for the
# reader's backend="auto" choice and parse_chunk dispatch.
NATIVE_FORMATS = {
    "libsvm": "ps_parse_libsvm",
    "criteo": "ps_parse_criteo",
    "adfea": "ps_parse_adfea",
}

_lib: ctypes.CDLL | None = None
_lib_tried = False


def _build() -> Path | None:
    so = _NATIVE_DIR / "libpsdata.so"
    src = _NATIVE_DIR / "parser.cpp"
    if not src.exists():  # deployed artifact without sources: use as-is
        return so if so.exists() else None
    if so.exists() and so.stat().st_mtime >= src.stat().st_mtime:
        return so
    try:
        subprocess.run(
            ["make", "-C", str(_NATIVE_DIR)],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return so if so.exists() else None
    except (subprocess.SubprocessError, OSError):
        return None


def load_native() -> ctypes.CDLL | None:
    """Load (building if needed) the native parser library, or None."""
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    _lib_tried = True
    path = os.environ.get(_LIB_ENV)
    so = Path(path) if path else _build()
    if so is None or not Path(so).exists():
        return None
    try:
        lib = ctypes.CDLL(str(so))
    except OSError:
        return None
    i64, u64p = ctypes.c_int64, ctypes.POINTER(ctypes.c_uint64)
    f32p, i64p = ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int64)
    for fn in NATIVE_FORMATS.values():
        f = getattr(lib, fn)
        f.restype = ctypes.c_int
        f.argtypes = [
            ctypes.c_char_p, i64,  # buf, len
            i64, i64,  # max_rows, max_nnz
            f32p, i64p,  # labels, row_splits
            u64p, f32p, u64p,  # keys, vals, slots
            i64p, i64p, i64p,  # out_rows, out_nnz, err_line
        ]
    try:
        hl = lib.ps_hash_localize
    except AttributeError:
        hl = None  # older prebuilt artifact without the kernel
    if hl is not None:
        hl.restype = ctypes.c_int
        hl.argtypes = [
            u64p, u64p, i64,  # raw keys, slots (or None), n
            ctypes.c_uint64, ctypes.c_int,  # num_keys, identity flag
            i64p, ctypes.POINTER(ctypes.c_int32), i64p,  # unique, inverse, n_uniq
        ]
    _lib = lib
    return _lib


def native_available() -> bool:
    return load_native() is not None


def hash_localize(
    raw_keys: np.ndarray,
    slots: np.ndarray | None,
    num_keys: int,
    identity: bool = False,
) -> tuple[np.ndarray, np.ndarray] | None:
    """GIL-free hash + localize (ref: the reference's C++ Localizer): hash
    raw keys into [1, num_keys) (or +1 in identity mode) and return
    (sorted unique gids int64, 0-based inverse int32) — exactly
    ``np.unique(hash_keys(...), return_inverse=True)``. Returns None when
    the kernel is unavailable or inapplicable (no library, num_keys >
    2^32, identity key out of range) — callers fall back to numpy, which
    also reproduces the exact error message for the range case."""
    lib = load_native()
    if lib is None or not hasattr(lib, "ps_hash_localize"):
        return None
    if num_keys < 2:
        return None  # numpy path owns the clean num_keys>=2 ValueError
    raw = np.ascontiguousarray(raw_keys, dtype=np.uint64)
    n = len(raw)
    unique = np.empty(max(n, 1), dtype=np.int64)
    inverse = np.empty(max(n, 1), dtype=np.int32)
    n_uniq = ctypes.c_int64()
    u64p = ctypes.POINTER(ctypes.c_uint64)
    sl = None
    if slots is not None:
        sl = np.ascontiguousarray(slots, dtype=np.uint64)
    rc = lib.ps_hash_localize(
        raw.ctypes.data_as(u64p),
        sl.ctypes.data_as(u64p) if sl is not None else None,
        n,
        ctypes.c_uint64(num_keys),
        1 if identity else 0,
        unique.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        inverse.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.byref(n_uniq),
    )
    if rc == -4:
        raise MemoryError("ps_hash_localize: allocation failed")
    if rc != 0:  # -3 identity range error, -5 num_keys > 2^32
        return None
    u = n_uniq.value
    return unique[:u], inverse[:n]


# Formats whose slot id is constant 0 (libsvm): the slots array is pure
# zeros, so the wrapper returns None instead of copying megabytes of
# zeros per chunk — downstream (BatchBuilder.build_flat) treats None as
# salt 0, which hashes identically.
SLOTLESS_FORMATS = frozenset({"libsvm"})

# Grow-only per-thread scratch for the parser outputs: fresh np.empty of
# ~80 MB per 8 MB chunk costs a page-fault storm every call (measured:
# the raw C parse runs ~480 MB/s but the old allocate-per-call wrapper
# delivered ~205). Real data is copied out, so reuse is safe. Slotless
# formats carry no slots scratch at all (the parser takes NULL).
_scratch = threading.local()


def _scratch_bufs(max_rows: int, max_nnz: int, want_slots: bool) -> dict:
    """Per-array grow-only: only undersized (or newly needed) buffers are
    reallocated, so the nnz-overflow retry and a format switch don't churn
    the still-valid large arrays."""
    s = getattr(_scratch, "bufs", None)
    if s is None:
        s = {"labels": None, "row_splits": None, "keys": None,
             "vals": None, "slots": None}
        _scratch.bufs = s
    if s["labels"] is None or len(s["labels"]) < max_rows:
        s["labels"] = np.empty(max_rows, dtype=np.float32)
        s["row_splits"] = np.empty(max_rows + 1, dtype=np.int64)
    if s["keys"] is None or len(s["keys"]) < max_nnz:
        s["keys"] = np.empty(max_nnz, dtype=np.uint64)
        s["vals"] = np.empty(max_nnz, dtype=np.float32)
        s["slots"] = np.empty(max_nnz, dtype=np.uint64) if want_slots else None
    elif want_slots and (s["slots"] is None or len(s["slots"]) < len(s["keys"])):
        s["slots"] = np.empty(len(s["keys"]), dtype=np.uint64)
    return s


def parse_chunk(fmt: str, chunk: bytes, max_rows_hint: int = 0) -> FlatRows:
    """Parse a buffer of complete lines via the C parser. ``slots`` in the
    returned tuple is None for SLOTLESS_FORMATS."""
    lib = load_native()
    if lib is None:
        raise RuntimeError("native parser not available")
    if not chunk.endswith(b"\n"):
        chunk += b"\n"
    if fmt not in NATIVE_FORMATS:
        raise ValueError(f"native parser: unknown format {fmt!r}")
    fn = getattr(lib, NATIVE_FORMATS[fmt])
    # capacity: rows from the newline count (exact bound; '\r' counts too —
    # the C parser splits rows on lone CR). Entries start from a realistic
    # ~6 bytes/entry estimate and double on overflow (the hard floor is 2
    # bytes/entry, but sizing scratch for it quadruples resident memory)
    max_rows = max(max_rows_hint, chunk.count(b"\n") + chunk.count(b"\r") + 1)
    max_nnz = max(64, len(chunk) // 6)
    hard_cap = max(64, len(chunk) // 2 + 1)
    want_slots = fmt not in SLOTLESS_FORMATS
    while True:
        s = _scratch_bufs(max_rows, max_nnz, want_slots)
        out_rows = ctypes.c_int64()
        out_nnz = ctypes.c_int64()
        err_line = ctypes.c_int64(-1)
        rc = fn(
            chunk,
            len(chunk),
            max_rows,
            len(s["keys"]),
            s["labels"].ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            s["row_splits"].ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            s["keys"].ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            s["vals"].ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            (
                s["slots"].ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))
                if want_slots
                else None
            ),
            ctypes.byref(out_rows),
            ctypes.byref(out_nnz),
            ctypes.byref(err_line),
        )
        if rc == -1 and len(s["keys"]) < hard_cap:
            max_nnz = min(2 * len(s["keys"]), hard_cap)
            continue
        break
    if rc == -1:
        raise RuntimeError("native parser capacity overflow (internal bug)")
    if rc == -2:
        raise ValueError(f"parse error at line {err_line.value} of chunk ({fmt})")
    r, n = out_rows.value, out_nnz.value
    return (
        s["labels"][:r].copy(),
        s["row_splits"][: r + 1].copy(),
        s["keys"][:n].copy(),
        s["vals"][:n].copy(),
        s["slots"][:n].copy() if want_slots else None,
    )


def iter_chunks(
    path: str | Path, fmt: str, chunk_bytes: int = 8 << 20
) -> Iterator[FlatRows]:
    """Stream a text file (optionally .gz) through the native parser."""
    import gzip

    p = Path(path)
    opener = gzip.open if p.suffix == ".gz" else open
    with opener(p, "rb") as f:
        tail = b""
        while True:
            buf = f.read(chunk_bytes)
            if not buf:
                if tail.strip():
                    yield parse_chunk(fmt, tail)
                return
            buf = tail + buf
            # cut at the last newline of either convention so CR-terminated
            # files stream in chunks instead of accumulating to EOF; a chunk
            # ending exactly at '\r' stays in the tail — the next read may
            # begin with '\n' (a CRLF split across chunk boundaries)
            stop = len(buf) - 1 if buf.endswith(b"\r") else len(buf)
            cut = max(buf.rfind(b"\n", 0, stop), buf.rfind(b"\r", 0, stop))
            if cut < 0:
                tail = buf
                continue
            tail = buf[cut + 1 :]
            yield parse_chunk(fmt, buf[: cut + 1])
