"""ctypes bindings for the native (C++) text parsers.

Reference analog: src/data/text_parser.cc — the reference's parsing is
C++; this keeps the rebuild's ingest hot path native too. The extension is
built on demand with ``make`` (g++); if unavailable, callers fall back to
the Python parsers in data/libsvm.py, which produce identical rows.

Chunked protocol: files are read in ~2 MiB chunks cut at line boundaries
(measured-best: chunk + its parsed outputs stay LLC-resident — 2 MiB runs
~1.2x faster than 8 MiB and ~2.4x faster than 32 MiB on the dev box);
each chunk is parsed in one C call into flat CSR arrays (labels,
row_splits, keys, vals, slots). The hot path is copy-free end to end:
readinto a reusable padded bytearray, AVX2 counts size the output arrays
exactly, and the C parser writes them directly (measured ~370 MB/s per
stream through this wrapper vs ~520 raw C on the 1-core dev box; the
pre-rewrite wrapper delivered ~210)."""

from __future__ import annotations

import ctypes
import os
import subprocess
from collections.abc import Iterator
from pathlib import Path

import numpy as np

_NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"
_LIB_ENV = "PS_TPU_NATIVE_LIB"

FlatRows = tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, "np.ndarray | None"]
# (labels (R,), row_splits (R+1,), keys (N,), vals (N,), slots (N,) or
#  None for SLOTLESS_FORMATS — all slot ids are 0 there)

# Formats with a native fast path; the single source of truth for the
# reader's backend="auto" choice and parse_chunk dispatch.
NATIVE_FORMATS = {
    "libsvm": "ps_parse_libsvm",
    "criteo": "ps_parse_criteo",
    "adfea": "ps_parse_adfea",
}

_lib: ctypes.CDLL | None = None
_lib_tried = False


def _build() -> Path | None:
    so = _NATIVE_DIR / "libpsdata.so"
    src = _NATIVE_DIR / "parser.cpp"
    if not src.exists():  # deployed artifact without sources: use as-is
        return so if so.exists() else None
    if so.exists() and so.stat().st_mtime >= src.stat().st_mtime:
        return so
    try:
        subprocess.run(
            ["make", "-C", str(_NATIVE_DIR)],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return so if so.exists() else None
    except (subprocess.SubprocessError, OSError):
        return None


def _tune_malloc() -> None:
    """Raise glibc's mmap threshold so the multi-MB per-chunk output
    arrays are served from the (warm, reusable) heap instead of fresh
    mmaps — each fresh mmap pays a page-fault per 4 KiB on first touch,
    measured at ~9% of ingest wall time. Process-wide, so honoring an
    escape hatch; the reference's C++ loaders get the same effect from
    arena reuse."""
    if os.environ.get("PS_TPU_NO_MALLOPT"):
        return
    try:
        libc = ctypes.CDLL(None)
        libc.mallopt(ctypes.c_int(-3), ctypes.c_int(256 << 20))  # M_MMAP_THRESHOLD
    except (OSError, AttributeError):
        pass  # non-glibc platform: harmless to skip


def load_native() -> ctypes.CDLL | None:
    """Load (building if needed) the native parser library, or None."""
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    _lib_tried = True
    _tune_malloc()
    path = os.environ.get(_LIB_ENV)
    so = Path(path) if path else _build()
    if so is None or not Path(so).exists():
        return None
    try:
        lib = ctypes.CDLL(str(so))
    except OSError:
        return None
    i64, u64p = ctypes.c_int64, ctypes.POINTER(ctypes.c_uint64)
    f32p, i64p = ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int64)
    for fn in NATIVE_FORMATS.values():
        f = getattr(lib, fn)
        f.restype = ctypes.c_int
        f.argtypes = [
            ctypes.c_char_p, i64,  # buf, len
            i64, i64,  # max_rows, max_nnz
            f32p, i64p,  # labels, row_splits
            u64p, f32p, u64p,  # keys, vals, slots
            i64p, i64p, i64p,  # out_rows, out_nnz, err_line
        ]
    try:
        c4 = lib.ps_count4
        c4.restype = None
        c4.argtypes = [
            ctypes.c_char_p, i64,
            ctypes.c_byte, ctypes.c_byte, ctypes.c_byte, ctypes.c_byte,
            i64p,
        ]
    except AttributeError:
        pass  # older prebuilt artifact: _counts falls back to bytes.count
    try:
        hl = lib.ps_hash_localize
    except AttributeError:
        hl = None  # older prebuilt artifact without the kernel
    if hl is not None:
        hl.restype = ctypes.c_int
        hl.argtypes = [
            u64p, u64p, i64,  # raw keys, slots (or None), n
            ctypes.c_uint64, ctypes.c_int,  # num_keys, identity flag
            i64p, ctypes.POINTER(ctypes.c_int32), i64p,  # unique, inverse, n_uniq
        ]
    _lib = lib
    return _lib


def native_available() -> bool:
    return load_native() is not None


def hash_localize(
    raw_keys: np.ndarray,
    slots: np.ndarray | None,
    num_keys: int,
    identity: bool = False,
) -> tuple[np.ndarray, np.ndarray] | None:
    """GIL-free hash + localize (ref: the reference's C++ Localizer): hash
    raw keys into [1, num_keys) (or +1 in identity mode) and return
    (sorted unique gids int64, 0-based inverse int32) — exactly
    ``np.unique(hash_keys(...), return_inverse=True)``. Returns None when
    the kernel is unavailable or inapplicable (no library, num_keys >
    2^32, identity key out of range) — callers fall back to numpy, which
    also reproduces the exact error message for the range case."""
    lib = load_native()
    if lib is None or not hasattr(lib, "ps_hash_localize"):
        return None
    if num_keys < 2:
        return None  # numpy path owns the clean num_keys>=2 ValueError
    raw = np.ascontiguousarray(raw_keys, dtype=np.uint64)
    n = len(raw)
    unique = np.empty(max(n, 1), dtype=np.int64)
    inverse = np.empty(max(n, 1), dtype=np.int32)
    n_uniq = ctypes.c_int64()
    u64p = ctypes.POINTER(ctypes.c_uint64)
    sl = None
    if slots is not None:
        sl = np.ascontiguousarray(slots, dtype=np.uint64)
    rc = lib.ps_hash_localize(
        raw.ctypes.data_as(u64p),
        sl.ctypes.data_as(u64p) if sl is not None else None,
        n,
        ctypes.c_uint64(num_keys),
        1 if identity else 0,
        unique.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        inverse.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.byref(n_uniq),
    )
    if rc == -4:
        raise MemoryError("ps_hash_localize: allocation failed")
    if rc != 0:  # -3 identity range error, -5 num_keys > 2^32
        return None
    u = n_uniq.value
    return unique[:u], inverse[:n]


# Formats whose slot id is constant 0 (libsvm): the slots array is pure
# zeros, so the wrapper returns None instead of copying megabytes of
# zeros per chunk — downstream (BatchBuilder.build_flat) treats None as
# salt 0, which hashes identically.
SLOTLESS_FORMATS = frozenset({"libsvm"})

# readable slack the C parsers may overread past the parse length (the
# AVX2 span parsers issue one unguarded 8-byte load per token)
_PAD = 8


# fourth needle per format for ps_count4 (first three are \n, \r, and the
# format's entry marker); counts[3] refines the entry bound for libsvm
# (space-preceded bare ``k`` entries) and adfea (ws-preceded entries)
_COUNT_NEEDLES = {"libsvm": b": ", "criteo": b"\t\0", "adfea": b" \t"}


def _counts(lib, fmt: str, ba: bytearray, length: int) -> tuple[int, int]:
    """(rows_cap, nnz_cap): exact row bound from the line-terminator
    count, entry bound from format-specific marker counts — one AVX2
    pass in C (python's bytes.count pays per-occurrence overhead that at
    CTR colon densities costs more than the parse itself). The output
    arrays are then allocated EXACTLY once and written by C directly (no
    scratch, no copy-out — measured, the copy-out pass was the largest
    wrapper cost). libsvm's colon count is exact except for bare ``k``
    entries (implicit 1.0) — those undershoot and take the grow retry in
    _parse_region."""
    c3, c4 = _COUNT_NEEDLES[fmt]
    if hasattr(lib, "ps_count4"):
        out = (ctypes.c_int64 * 4)()
        lib.ps_count4(
            (ctypes.c_char * len(ba)).from_buffer(ba), length,
            0x0A, 0x0D, c3, c4, out,
        )
        out = list(out)
    else:  # older prebuilt artifact
        out = [ba.count(bytes([c]), 0, length) for c in (0x0A, 0x0D, c3, c4)]
    rows_cap = out[0] + out[1] + 1
    if fmt == "libsvm":
        # colons are exact for ``k:v`` entries; bare ``k`` entries carry no
        # colon but are each preceded by >= 1 space, so the space count is
        # the complementary bound — max of the two avoids the grow-retry
        # cliff on colon-free chunks (tab-separated bare keys still
        # undershoot and take the retry, whose jump below is linear)
        nnz_cap = max(out[2], out[3]) + 1
    elif fmt == "criteo":
        nnz_cap = 39 * rows_cap + 1  # hard bound: <= 39 features per row
    else:  # adfea: every entry is preceded by at least one ws byte
        nnz_cap = out[2] + out[3] + 1
    return rows_cap, nnz_cap


def _parse_region(fmt: str, ba: bytearray, length: int) -> FlatRows:
    """Parse ba[:length] (complete lines; last byte a line terminator;
    ba must extend >= _PAD bytes past length). The region is passed by
    POINTER — no slice copy — and outputs are written by the C parser
    straight into exactly-sized fresh arrays."""
    lib = load_native()
    if lib is None:
        raise RuntimeError("native parser not available")
    if fmt not in NATIVE_FORMATS:
        raise ValueError(f"native parser: unknown format {fmt!r}")
    fn = getattr(lib, NATIVE_FORMATS[fmt])
    rows_cap, nnz_cap = _counts(lib, fmt, ba, length)
    want_slots = fmt not in SLOTLESS_FORMATS
    buf_p = (ctypes.c_char * len(ba)).from_buffer(ba)
    while True:
        labels = np.empty(rows_cap, dtype=np.float32)
        splits = np.empty(rows_cap + 1, dtype=np.int64)
        keys = np.empty(nnz_cap, dtype=np.uint64)
        vals = np.empty(nnz_cap, dtype=np.float32)
        slots = np.empty(nnz_cap, dtype=np.uint64) if want_slots else None
        out_rows = ctypes.c_int64()
        out_nnz = ctypes.c_int64()
        err_line = ctypes.c_int64(-1)
        rc = fn(
            buf_p,
            length,
            rows_cap,
            nnz_cap,
            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            splits.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            (
                slots.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))
                if want_slots
                else None
            ),
            ctypes.byref(out_rows),
            ctypes.byref(out_nnz),
            ctypes.byref(err_line),
        )
        if rc == -1:
            # nnz bound undershoot (bare-key libsvm): rows_cap is exact
            # (newline count), so only the entry bound can overflow. Jump
            # straight to a bytes-per-entry estimate (entries are >= ~6
            # bytes in practice) so a badly-undershot seed converges in
            # one or two retries instead of O(log n) full re-parses. The
            # hard floor is 2 bytes/entry; hitting it twice means the C
            # side's capacity accounting is broken — raise, don't spin
            new_cap = min(max(2 * nnz_cap + 64, length // 6), length // 2 + 1)
            if new_cap == nnz_cap:
                raise RuntimeError(
                    "native parser capacity overflow (internal bug)"
                )
            nnz_cap = new_cap
            continue
        break
    if rc == -2:
        raise ValueError(f"parse error at line {err_line.value} of chunk ({fmt})")
    if rc != 0:
        raise RuntimeError(f"native parser failed (rc={rc}, fmt={fmt})")
    r, n = out_rows.value, out_nnz.value
    # views, not copies: the arrays are freshly allocated per call and
    # exactly sized up to blank-line slack
    return (
        labels[:r],
        splits[: r + 1],
        keys[:n],
        vals[:n],
        slots[:n] if want_slots else None,
    )


def parse_chunk(fmt: str, chunk: bytes, max_rows_hint: int = 0) -> FlatRows:
    """Parse a buffer of complete lines via the C parser. ``slots`` in the
    returned tuple is None for SLOTLESS_FORMATS. (max_rows_hint is
    retained for API compatibility; capacities are exact now.)"""
    del max_rows_hint
    length = len(chunk)
    ba = bytearray(length + 1 + _PAD)
    ba[:length] = chunk
    if length == 0 or chunk[-1:] not in (b"\n", b"\r"):
        ba[length] = 0x0A  # the C parsers require closed lines
        length += 1
    return _parse_region(fmt, ba, length)


def iter_chunks(
    path: str | Path, fmt: str, chunk_bytes: int = 2 << 20
) -> Iterator[FlatRows]:
    """Stream a text file (optionally .gz) through the native parser.

    Zero-copy streaming: one reusable bytearray holds [carried tail |
    fresh read | pad]; reads land via readinto, the parsed region is
    passed to C by pointer, and only the sub-line tail is memmoved to the
    front between chunks — the old bytes-concatenate + slice path copied
    every byte twice per chunk."""
    import gzip

    p = Path(path)
    opener = gzip.open if p.suffix == ".gz" else open
    with opener(p, "rb") as f:
        cap = chunk_bytes + (chunk_bytes >> 2) + _PAD
        ba = bytearray(cap)
        mv = memoryview(ba)
        tail = 0
        while True:
            if tail + _PAD + 1 >= cap:  # single line longer than the buffer
                cap *= 2
                nba = bytearray(cap)
                nba[:tail] = mv[:tail]
                ba, mv = nba, memoryview(nba)
            # reserve _PAD + 1 bytes past the read: the EOF branch may
            # append a closing 0x0A, and the appended terminator must
            # still leave the full _PAD slack _parse_region documents
            n = f.readinto(mv[tail : cap - _PAD - 1])
            total = tail + (n or 0)
            if not n:
                if total and bytes(mv[:total]).strip():
                    if ba[total - 1] not in (0x0A, 0x0D):
                        ba[total] = 0x0A
                        total += 1
                    yield _parse_region(fmt, ba, total)
                return
            # cut at the last newline of either convention so CR-terminated
            # files stream in chunks instead of accumulating to EOF; a chunk
            # ending exactly at '\r' stays in the tail — the next read may
            # begin with '\n' (a CRLF split across chunk boundaries)
            stop = total - 1 if ba[total - 1] == 0x0D else total
            cut = max(ba.rfind(b"\n", 0, stop), ba.rfind(b"\r", 0, stop))
            if cut < 0:
                tail = total
                continue
            yield _parse_region(fmt, ba, cut + 1)
            rest = total - (cut + 1)
            if 0 < rest <= cut + 1:  # disjoint ranges: plain slice copy
                mv[:rest] = mv[cut + 1 : total]
            elif rest:  # tail longer than the parsed prefix (huge line):
                # materialize first — overlapping memoryview assignment is
                # memcpy underneath, and overlap direction is unspecified
                mv[:rest] = bytes(mv[cut + 1 : total])
            tail = rest
