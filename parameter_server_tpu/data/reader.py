"""Streaming minibatch reader with prefetch.

Reference analog: learner/sgd.h MinibatchReader (parser thread feeding a
threadsafe queue) + data/stream_reader.h (multi-file, gz-aware streaming).
"""

from __future__ import annotations

import queue
import threading
from collections.abc import Iterator
from pathlib import Path

import numpy as np

from parameter_server_tpu.data.batch import BatchBuilder, CSRBatch
from parameter_server_tpu.data.libsvm import iter_format


class MinibatchReader:
    """Streams CSRBatches from text files through a prefetch thread.

    ``epochs`` and ``drop_remainder`` control the stream; a worker id /
    num_workers pair shards *files* across workers the way the reference's
    WorkloadPool hands file shards to workers (ref: learner/workload_pool.h).
    """

    def __init__(
        self,
        files: list[str | Path],
        fmt: str,
        builder: BatchBuilder,
        epochs: int = 1,
        prefetch: int = 4,
        worker_id: int = 0,
        num_workers: int = 1,
        drop_remainder: bool = False,
        backend: str = "auto",  # auto | native | python
    ):
        if not files:
            raise ValueError("no input files")
        if backend not in ("auto", "native", "python"):
            raise ValueError(f"bad backend {backend!r}")
        self.files = [f for i, f in enumerate(sorted(map(str, files))) if i % num_workers == worker_id]
        self.fmt = fmt
        self.builder = builder
        self.epochs = epochs
        self.prefetch = prefetch
        self.drop_remainder = drop_remainder
        from parameter_server_tpu.data import native as _native

        self.use_native = backend == "native" or (
            backend == "auto"
            and fmt in _native.NATIVE_FORMATS
            and _native.native_available()
        )
        if backend == "native" and not _native.native_available():
            raise RuntimeError("native parser requested but not available")

    def _epoch_rows(self) -> Iterator:
        for f in self.files:
            yield from iter_format(self.fmt, f)

    def _flat_batches(self) -> Iterator[CSRBatch]:
        """Native path: C++ chunk parse -> vectorized batch slicing."""
        from parameter_server_tpu.data.native import iter_chunks

        bs, nnz_cap = self.builder.batch_size, self.builder.nnz_capacity

        def take(slots, sl):
            # slots is None for slotless formats (native.SLOTLESS_FORMATS)
            return None if slots is None else slots[sl]

        def slices(flat):
            """Yield CSRBatches of full size from ``flat``; return leftover."""
            labels, splits, keys, vals, slots = flat
            i = 0
            n = len(labels)
            while i < n:
                # largest j with rows<=bs and entries<=nnz_cap
                j_row = min(n, i + bs)
                base = splits[i]
                j = int(
                    np.searchsorted(splits, base + nnz_cap, side="right") - 1
                )
                j = max(i + 1, min(j_row, j))
                if j < n or (n - i) >= bs:
                    yield self.builder.build_flat(
                        labels[i:j],
                        (splits[i : j + 1] - base),
                        keys[base : splits[j]],
                        vals[base : splits[j]],
                        take(slots, slice(base, splits[j])),
                    )
                    i = j
                else:
                    break  # tail smaller than a batch: keep pending
            base = splits[i]
            return (
                labels[i:],
                splits[i:] - base,
                keys[base:],
                vals[base:],
                take(slots, slice(base, None)),
            )

        def cat(a, b):
            la, sa, ka, va, oa = a
            lb, sb, kb, vb, ob = b
            return (
                np.concatenate([la, lb]),
                np.concatenate([sa, sb[1:] + sa[-1]]),
                np.concatenate([ka, kb]),
                np.concatenate([va, vb]),
                # slots-ness is per-format, fixed per reader: both sides
                # always agree
                None if oa is None else np.concatenate([oa, ob]),
            )

        for _ in range(self.epochs):
            leftover = None
            for f in self.files:
                for flat in iter_chunks(f, self.fmt):
                    merged = cat(leftover, flat) if leftover is not None else flat
                    gen = slices(merged)
                    while True:
                        try:
                            yield next(gen)
                        except StopIteration as s:
                            leftover = s.value
                            break
            # epoch boundary flushes (epochs=N == N runs of epochs=1)
            if leftover is not None and len(leftover[0]) and not self.drop_remainder:
                yield self.builder.build_flat(*leftover)

    def _batches(self) -> Iterator[CSRBatch]:
        if self.use_native:
            yield from self._flat_batches()
            return
        for _ in range(self.epochs):
            labels: list[float] = []
            keys: list[np.ndarray] = []
            vals: list[np.ndarray] = []
            slots: list[np.ndarray] = []
            nnz = 0
            for label, k, v, s in self._epoch_rows():
                # flush if the next row would overflow either capacity
                if labels and (
                    len(labels) == self.builder.batch_size
                    or nnz + len(k) > self.builder.nnz_capacity
                ):
                    yield self.builder.build(np.array(labels), keys, vals, slots)
                    labels, keys, vals, slots, nnz = [], [], [], [], 0
                labels.append(label)
                keys.append(k)
                vals.append(v)
                slots.append(s)
                nnz += len(k)
            if labels and not self.drop_remainder:
                yield self.builder.build(np.array(labels), keys, vals, slots)

    def __iter__(self) -> Iterator[CSRBatch]:
        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        _END = object()
        err: list[BaseException] = []
        stop = threading.Event()

        def _put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce() -> None:
            try:
                for b in self._batches():
                    if not _put(b):
                        return  # consumer abandoned iteration
            except BaseException as e:  # surfaced on the consumer side
                err.append(e)
            finally:
                _put(_END)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            # unstick the producer if the consumer broke out early
            stop.set()


def iter_flat_rows(files: list[str | Path], fmt: str):
    """Yield flat CSR chunks ``(labels, row_splits, keys, vals, slots)`` from
    text files — the raw-key stream consumed by ingest-side components that
    don't need batches (frequency filter warmup, the sketch app). Native
    chunk parser when available, else the Python row parsers. ``slots`` is
    None for slotless formats (native.SLOTLESS_FORMATS — all slot ids are
    0 there) on BOTH backends, so consumers see one contract."""
    from parameter_server_tpu.data import native as _native

    paths = sorted(map(str, files))
    if fmt in _native.NATIVE_FORMATS and _native.native_available():
        for f in paths:
            yield from _native.iter_chunks(f, fmt)
        return
    from parameter_server_tpu.data.libsvm import iter_format

    for f in paths:
        labels, splits, keys, vals, slots = [], [0], [], [], []
        for label, k, v, s in iter_format(fmt, f):
            labels.append(label)
            splits.append(splits[-1] + len(k))
            keys.append(k)
            vals.append(v)
            slots.append(s)
        if labels:
            yield (
                np.asarray(labels, dtype=np.float32),
                np.asarray(splits, dtype=np.int64),
                np.concatenate(keys) if keys else np.zeros(0, np.uint64),
                np.concatenate(vals) if vals else np.zeros(0, np.float32),
                (
                    None
                    if fmt in _native.SLOTLESS_FORMATS
                    else np.concatenate(slots)
                    if slots
                    else np.zeros(0, np.uint64)
                ),
            )
