"""Streaming minibatch reader with prefetch.

Reference analog: learner/sgd.h MinibatchReader (parser thread feeding a
threadsafe queue) + data/stream_reader.h (multi-file, gz-aware streaming).
"""

from __future__ import annotations

import queue
import threading
from collections.abc import Iterator
from pathlib import Path

import numpy as np

from parameter_server_tpu.data.batch import BatchBuilder, CSRBatch
from parameter_server_tpu.data.libsvm import iter_format


class MinibatchReader:
    """Streams CSRBatches from text files through a prefetch thread.

    ``epochs`` and ``drop_remainder`` control the stream; a worker id /
    num_workers pair shards *files* across workers the way the reference's
    WorkloadPool hands file shards to workers (ref: learner/workload_pool.h).
    """

    def __init__(
        self,
        files: list[str | Path],
        fmt: str,
        builder: BatchBuilder,
        epochs: int = 1,
        prefetch: int = 4,
        worker_id: int = 0,
        num_workers: int = 1,
        drop_remainder: bool = False,
    ):
        if not files:
            raise ValueError("no input files")
        self.files = [f for i, f in enumerate(sorted(map(str, files))) if i % num_workers == worker_id]
        self.fmt = fmt
        self.builder = builder
        self.epochs = epochs
        self.prefetch = prefetch
        self.drop_remainder = drop_remainder

    def _rows(self) -> Iterator:
        for _ in range(self.epochs):
            for f in self.files:
                yield from iter_format(self.fmt, f)

    def _batches(self) -> Iterator[CSRBatch]:
        labels: list[float] = []
        keys: list[np.ndarray] = []
        vals: list[np.ndarray] = []
        slots: list[np.ndarray] = []
        nnz = 0
        for label, k, v, s in self._rows():
            # flush if the next row would overflow either capacity
            if labels and (
                len(labels) == self.builder.batch_size
                or nnz + len(k) > self.builder.nnz_capacity
            ):
                yield self.builder.build(np.array(labels), keys, vals, slots)
                labels, keys, vals, slots, nnz = [], [], [], [], 0
            labels.append(label)
            keys.append(k)
            vals.append(v)
            slots.append(s)
            nnz += len(k)
        if labels and not self.drop_remainder:
            yield self.builder.build(np.array(labels), keys, vals, slots)

    def __iter__(self) -> Iterator[CSRBatch]:
        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        _END = object()
        err: list[BaseException] = []
        stop = threading.Event()

        def _put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce() -> None:
            try:
                for b in self._batches():
                    if not _put(b):
                        return  # consumer abandoned iteration
            except BaseException as e:  # surfaced on the consumer side
                err.append(e)
            finally:
                _put(_END)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            # unstick the producer if the consumer broke out early
            stop.set()
