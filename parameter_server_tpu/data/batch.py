"""Static-shape CSR minibatches + the localizer.

Reference analog: src/app/linear_method/localizer.h — per block/minibatch,
``unique`` the touched global keys and remap entries to dense local ids so
the compute kernel works on a small dense index space; the unique key list
is what Pull/Push are issued against.

TPU twist: every batch is padded to static (B, NNZ, U) so one compiled
program serves the whole stream. Padding contract (see kv.store):
  - ``unique_keys[0] == PAD_KEY (0)`` always; unused unique slots repeat 0.
  - padded CSR entries have ``value == 0`` and point at unique slot 0, row 0.
  - padded example rows have ``label == 0`` and ``example_mask == False``.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from parameter_server_tpu.utils.hashing import PAD_KEY, hash_keys


@dataclass
class CSRBatch:
    """One device-ready minibatch. All arrays have static shapes.

    ``unique_keys`` is int32 whenever num_keys fits (practically always)
    and ``row_splits`` carries the same row structure as ``row_ids`` in
    B+1 ints instead of NNZ — together the compact wire format
    (parallel.spmd CSR_COMPACT_FIELDS) that cuts host->device bytes ~40%
    at typical densities; the device rebuilds row_ids with one
    searchsorted. The reference ships raw int64 keys + per-entry row ids
    over ZeroMQ and leans on its filter pipeline instead (src/filter/);
    here the transfer layout itself is the filter."""

    unique_keys: np.ndarray  # (U,) int32/int64 — hashed global ids, slot 0 = pad
    local_ids: np.ndarray  # (NNZ,) int32 — entry -> unique slot
    row_ids: np.ndarray  # (NNZ,) int32 — entry -> example row
    values: np.ndarray  # (NNZ,) float32
    labels: np.ndarray  # (B,) float32 in {0, 1}
    example_mask: np.ndarray  # (B,) bool
    row_splits: np.ndarray  # (B+1,) int32 — cumulative real entries per row
    num_examples: int
    num_unique: int  # real unique keys (including pad slot 0)
    num_entries: int

    @property
    def shape(self) -> tuple[int, int, int]:
        return (len(self.labels), len(self.values), len(self.unique_keys))


def training_builder(cfg, key_mode: str = "hash") -> "BatchBuilder":
    """The training-ingest builder for a PSConfig: wires the frequency
    filter (cfg.data.freq_min_count + [sketch] geometry) into admission.
    Eval paths build plain BatchBuilders — unadmitted keys carry zero
    weight, so filtering there would be pointless work."""
    freq_filter = None
    if cfg.data.freq_min_count > 0:
        from parameter_server_tpu.filters.frequency import CountMinSketch

        freq_filter = CountMinSketch(cfg.sketch.width, cfg.sketch.depth)
    return BatchBuilder(
        num_keys=cfg.data.num_keys,
        batch_size=cfg.solver.minibatch,
        max_nnz_per_example=cfg.data.max_nnz_per_example,
        key_mode=key_mode,
        freq_filter=freq_filter,
        freq_min_count=cfg.data.freq_min_count,
        bucket_nnz=cfg.data.bucket_nnz,
    )


def eval_builder(cfg, key_mode: str = "hash") -> "BatchBuilder":
    """The evaluation-ingest builder: NO frequency admission. A fresh
    filter would restart every key at count 0 and silently drop entries
    for keys the model actually trained on, skewing val metrics; and
    unadmitted keys carry zero weight anyway, so filtering eval input is
    pointless work either way."""
    return BatchBuilder(
        num_keys=cfg.data.num_keys,
        batch_size=cfg.solver.minibatch,
        max_nnz_per_example=cfg.data.max_nnz_per_example,
        key_mode=key_mode,
        bucket_nnz=cfg.data.bucket_nnz,
    )


# bucketed batches never shrink below this many entries: tiny buckets buy
# nothing and each distinct shape costs one jit compile
BUCKET_FLOOR = 2048


def _nnz_bucket(n: int, cap: int, floor: int = BUCKET_FLOOR) -> int:
    """Smallest power-of-two >= n (>= floor), capped at the static max."""
    b = max(floor, 1 << max(n - 1, 0).bit_length())
    return min(b, cap)


def pad_group(batches: list["CSRBatch"]) -> list["CSRBatch"]:
    """Bring a group of (possibly bucketed) batches to one static shape —
    the group max per dimension (buckets are powers of two, so the set of
    group shapes stays small). Used before stacking D shards."""
    nnz_t = max(len(b.values) for b in batches)
    u_t = max(len(b.unique_keys) for b in batches)
    return [pad_batch(b, nnz_t, u_t) for b in batches]


def zero_extend(a: np.ndarray, n: int, axis: int = 0) -> np.ndarray:
    """Zero-pad ``a`` to length ``n`` along ``axis`` — THE inert-padding
    primitive (zeros are inert everywhere by the PAD_KEY == slot 0
    convention); every grow path must come through here so the pad
    sentinel lives in one place."""
    if a.shape[axis] == n:
        return a
    if a.shape[axis] > n:
        raise ValueError(f"cannot shrink axis {axis}: {a.shape[axis]} > {n}")
    pad = [(0, 0)] * a.ndim
    pad[axis] = (0, n - a.shape[axis])
    return np.pad(a, pad)


def pad_batch(b: CSRBatch, nnz_cap: int, u_cap: int) -> CSRBatch:
    """Re-pad a (possibly bucketed) batch to the given capacities — used
    to bring a group of differently-bucketed batches to one static shape
    before stacking."""
    if len(b.values) == nnz_cap and len(b.unique_keys) == u_cap:
        return b
    if len(b.values) > nnz_cap or len(b.unique_keys) > u_cap:
        raise ValueError(
            f"cannot shrink batch ({len(b.values)}, {len(b.unique_keys)}) "
            f"to ({nnz_cap}, {u_cap})"
        )
    return CSRBatch(
        unique_keys=zero_extend(b.unique_keys, u_cap),
        local_ids=zero_extend(b.local_ids, nnz_cap),
        row_ids=zero_extend(b.row_ids, nnz_cap),
        values=zero_extend(b.values, nnz_cap),
        labels=b.labels,
        example_mask=b.example_mask,
        row_splits=b.row_splits,  # fixed (B+1,): counts real entries only
        num_examples=b.num_examples,
        num_unique=b.num_unique,
        num_entries=b.num_entries,
    )


class BatchBuilder:
    """Turns parsed (label, keys, values) rows into CSRBatches.

    key_mode:
      "hash"     — splitmix64 into [1, num_keys) (production path; slots salt)
      "identity" — key+1 used directly (exact parity runs vs sklearn; requires
                   raw keys < num_keys - 1)
    """

    def __init__(
        self,
        num_keys: int,
        batch_size: int,
        max_nnz_per_example: int = 256,
        unique_capacity: int | None = None,
        key_mode: str = "hash",
        freq_filter=None,
        freq_min_count: int = 0,
        bucket_nnz: bool = False,
    ):
        if key_mode not in ("hash", "identity"):
            raise ValueError(f"bad key_mode {key_mode!r}")
        self.num_keys = num_keys
        self.batch_size = batch_size
        self.nnz_capacity = batch_size * max_nnz_per_example
        # +1 for the pad slot; capped at nnz (can't see more uniques than entries)
        self.unique_capacity = unique_capacity or min(
            self.nnz_capacity + 1, num_keys
        )
        self.key_mode = key_mode
        # bucketed static shapes (TPU idiom): pad entry/unique arrays to
        # the next power of two above the REAL count instead of the worst
        # case — host->device bytes track actual density, and jit compiles
        # once per bucket (a handful of shapes), not per batch
        self.bucket_nnz = bucket_nnz
        # streaming admission (ref: parameter/frequency_filter.h — only
        # admit keys seen >= k times; at 10^9-key CTR scale the tail is
        # noise). The sketch counts RAW pre-hash keys as they stream by;
        # entries below the threshold are dropped before localization.
        self.freq_filter = freq_filter
        self.freq_min_count = freq_min_count
        if freq_min_count > 0 and freq_filter is None:
            from parameter_server_tpu.filters.frequency import CountMinSketch

            self.freq_filter = CountMinSketch()

    def build(
        self,
        labels: np.ndarray,
        keys: list[np.ndarray],
        values: list[np.ndarray],
        slot_ids: list[np.ndarray] | None = None,
    ) -> CSRBatch:
        """labels: (b,); keys[i]/values[i]: per-example sparse features."""
        counts = np.array([len(k) for k in keys], dtype=np.int64)
        row_splits = np.zeros(len(labels) + 1, dtype=np.int64)
        np.cumsum(counts, out=row_splits[1:])
        nnz = int(row_splits[-1])
        return self.build_flat(
            np.asarray(labels),
            row_splits,
            np.concatenate(keys) if nnz else np.zeros(0, dtype=np.uint64),
            (
                np.concatenate(values).astype(np.float32)
                if nnz
                else np.zeros(0, dtype=np.float32)
            ),
            np.concatenate(slot_ids) if slot_ids is not None else None,
        )

    def build_flat(
        self,
        labels: np.ndarray,
        row_splits: np.ndarray,
        flat_keys: np.ndarray,
        flat_vals: np.ndarray,
        flat_slots: np.ndarray | None = None,
    ) -> CSRBatch:
        """Vectorized build from flat CSR arrays (the native-parser path)."""
        b = len(labels)
        if b > self.batch_size:
            raise ValueError(f"{b} examples > batch_size {self.batch_size}")
        nnz = int(row_splits[-1])
        if nnz > self.nnz_capacity:
            raise ValueError(f"{nnz} entries > nnz capacity {self.nnz_capacity}")
        flat_vals = np.asarray(flat_vals, dtype=np.float32)
        row_ids = np.repeat(
            np.arange(b, dtype=np.int32), np.diff(row_splits).astype(np.int64)
        )

        splits_src = row_splits  # reusable unless the filter drops entries
        if self.freq_min_count > 0 and nnz:
            # count first (whole batch), then admit: a key is admitted —
            # including all its occurrences WITHIN this batch — once its
            # running count crosses the threshold. Admission is
            # batch-granular, not per-occurrence; occurrences in batches
            # before the crossing are sacrificed (the tail-filtering the
            # reference's frequency filter exists for)
            raw = np.asarray(flat_keys, dtype=np.uint64)
            self.freq_filter.add(raw)
            keep = self.freq_filter.admit(raw, self.freq_min_count)
            flat_keys = raw[keep]
            flat_vals = flat_vals[keep]
            row_ids = row_ids[keep]
            if flat_slots is not None:
                flat_slots = np.asarray(flat_slots)[keep]
            nnz = int(keep.sum())
            splits_src = None  # row structure changed; rederive below

        # Localizer: unique + inverse, with the pad key forced into slot 0
        # (ref: localizer.h). The native kernel fuses hash + sort-unique
        # with the GIL released (builder threads scale across cores); the
        # numpy path below is the exact-parity fallback.
        from parameter_server_tpu.data import native as _native

        nat = (
            _native.hash_localize(
                flat_keys, flat_slots, self.num_keys,
                identity=self.key_mode != "hash",
            )
            if nnz
            else None
        )
        if nat is not None:
            uniq, inverse = nat
        else:
            if self.key_mode == "hash":
                salts = flat_slots if flat_slots is not None else 0
                gids = hash_keys(flat_keys, self.num_keys, slot_ids=salts)
            else:
                gids = np.asarray(flat_keys, dtype=np.int64) + 1
                if nnz and gids.max() >= self.num_keys:
                    raise ValueError(
                        f"identity key {gids.max() - 1} >= num_keys-1; "
                        "grow num_keys or use key_mode='hash'"
                    )
            uniq, inverse = np.unique(gids, return_inverse=True)

        # Keys ride the wire as int32 whenever the key space fits (always,
        # short of a >2^31 dense space) — half the per-unique bytes.
        key_dtype = (
            np.int32 if self.num_keys <= np.iinfo(np.int32).max else np.int64
        )
        n_uniq = len(uniq) + 1  # + the forced PAD row at slot 0
        if n_uniq > self.unique_capacity:
            raise ValueError(
                f"{n_uniq} unique keys > capacity {self.unique_capacity}"
            )

        if self.bucket_nnz:
            nnz_cap = _nnz_bucket(nnz, self.nnz_capacity)
            u_cap = min(nnz_cap + 1, self.unique_capacity, self.num_keys)
        else:
            nnz_cap = self.nnz_capacity
            u_cap = self.unique_capacity
        # np.empty + explicit pad-tail zeroing, writing each entry ONCE:
        # np.zeros-then-overwrite double-writes the big per-entry arrays
        # (~1.5 MB/batch of pure zeroing at CTR densities), and the +1 /
        # PAD-prepend intermediates each cost another full copy — this
        # assembly glue, not the C localizer, bounds ingest (measured)
        out = CSRBatch(
            unique_keys=np.empty(u_cap, dtype=key_dtype),
            local_ids=np.empty(nnz_cap, dtype=np.int32),
            row_ids=np.empty(nnz_cap, dtype=np.int32),
            values=np.empty(nnz_cap, dtype=np.float32),
            labels=np.zeros(self.batch_size, dtype=np.float32),
            example_mask=np.zeros(self.batch_size, dtype=bool),
            row_splits=np.zeros(self.batch_size + 1, dtype=np.int32),
            num_examples=b,
            num_unique=n_uniq,
            num_entries=nnz,
        )
        out.unique_keys[0] = PAD_KEY
        out.unique_keys[1:n_uniq] = uniq  # downcast copy, no intermediate
        out.unique_keys[n_uniq:] = PAD_KEY
        # local ids shift by one for the PAD row, written straight into
        # the output (int64 numpy-fallback inverses narrow safely: ids
        # are bounded by unique_capacity)
        np.add(inverse, 1, out=out.local_ids[:nnz], casting="unsafe")
        out.local_ids[nnz:] = 0
        out.row_ids[:nnz] = row_ids
        out.row_ids[nnz:] = 0
        out.values[:nnz] = flat_vals
        out.values[nnz:] = 0.0
        out.labels[:b] = np.asarray(labels, dtype=np.float32)
        out.example_mask[:b] = True
        # compact row structure: same information as row_ids in B+1 ints
        # (row_ids over REAL entries is non-decreasing by construction)
        if splits_src is not None:
            out.row_splits[: b + 1] = splits_src  # unfiltered: caller's splits
        elif nnz:
            np.cumsum(
                np.bincount(row_ids, minlength=b), out=out.row_splits[1 : b + 1]
            )
        out.row_splits[b + 1 :] = out.row_splits[b]
        return out
