"""Data ingestion (reference analog: src/data/).

The reference parses text formats (libsvm / criteo / adfea) into slot-based
Example protos, then per minibatch remaps global keys to dense local ids
(Localizer) so workers compute with small dense indices. Here the same
pipeline produces static-shape ``CSRBatch``es ready for jit:

  text -> (label, keys, values) rows        parsers (Python + C++ ext)
       -> hashed global ids                 utils.hashing
       -> unique + inverse (localizer)      batch.make_csr_batch
       -> padded CSR minibatch              CSRBatch (static B/NNZ/U)
"""

from parameter_server_tpu.data.batch import BatchBuilder, CSRBatch  # noqa: F401
from parameter_server_tpu.data.libsvm import iter_libsvm  # noqa: F401
from parameter_server_tpu.data.reader import MinibatchReader  # noqa: F401
