"""Parallel prefetching host input pipeline.

Reference analog: learner/sgd.h — each SGD worker runs a parser thread
feeding a threadsafe minibatch queue so gradient compute never waits on
text parsing (SURVEY §2.2 threading/queues, §7.4 "the C++ parser must
sustain ≥ GB/s/host"). That feed structure is what keeps reference
workers busy; this module is its pod analog.

Topology: D builder threads (one per worker stream, each owning its own
stateful BatchBuilder so admission filters stay single-threaded) push
per-worker batches into per-stream bounded queues; one stacker thread
assembles them into ready global step items — stacked arrays plus the
host-side bookkeeping (example counts, labels) — in a bounded output
queue. The dispatch loop then only pops + dispatches the device step,
overlapping host parse/build with device compute instead of serializing
D batch builds inline before every step.

Draining contract: ``get()`` returns ``None`` once every stream is
exhausted (and forever after). Callers that must keep issuing collectives
(multi-host SPMD: every process runs the same program) substitute their
own inert batches after that.
"""

from __future__ import annotations

import queue
import threading
from collections.abc import Callable, Sequence
from typing import Any

_END = object()


class PrefetchPipeline:
    """Bounded parallel producer of ready-to-dispatch global step items.

    streams: objects exposing ``next_batch() -> batch | None`` (None =
        drained) and ``_empty() -> batch`` (inert all-padding batch).
    prepare: ``prepare(batches: list) -> item`` run on the stacker thread —
        the per-step host work (stacking, label bookkeeping) moved off the
        dispatch loop.
    depth: bound of every internal queue (per-stream and output).
    group_size / assemble: multistep grouping ON the stacker thread —
        every ``group_size`` prepared items are combined by
        ``assemble(items) -> group_item`` before emission, so the K-way
        group stacking (one device call's worth of microsteps) never runs
        on the dispatch loop. A partial final group is padded with
        prepared inert items (empties only ever trail real batches —
        the termination contract's invariant).
    """

    def __init__(
        self,
        streams: Sequence[Any],
        prepare: Callable[[list], Any],
        depth: int = 2,
        group_size: int = 1,
        assemble: Callable[[list], Any] | None = None,
    ):
        if depth < 1:
            raise ValueError(f"pipeline depth must be >= 1, got {depth}")
        if group_size > 1 and assemble is None:
            raise ValueError("group_size > 1 requires an assemble callable")
        self.streams = list(streams)
        self.prepare = prepare
        self.group_size = group_size
        self.assemble = assemble
        self._qs = [queue.Queue(maxsize=depth) for _ in self.streams]
        self._out: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._errs: list[BaseException] = []
        self._drained = False
        self._threads = [
            threading.Thread(target=self._produce, args=(i,), daemon=True)
            for i in range(len(self.streams))
        ]
        self._threads.append(
            threading.Thread(target=self._stack_loop, daemon=True)
        )
        for t in self._threads:
            t.start()

    # -- queue helpers that respect shutdown ------------------------------
    def _put(self, q: queue.Queue, item) -> bool:
        while not self._stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _get(self, q: queue.Queue):
        while not self._stop.is_set():
            try:
                return q.get(timeout=0.1)
            except queue.Empty:
                continue
        return _END

    # -- threads -----------------------------------------------------------
    def _produce(self, i: int) -> None:
        try:
            while not self._stop.is_set():
                b = self.streams[i].next_batch()
                if b is None:
                    break
                if not self._put(self._qs[i], b):
                    return
        except BaseException as e:  # re-raised on the consumer side
            self._errs.append(e)
        finally:
            self._put(self._qs[i], _END)

    def _stack_loop(self) -> None:
        done = [False] * len(self.streams)
        pending: list = []  # partially-filled multistep group
        try:
            while not self._stop.is_set():
                batches = []
                for i, q in enumerate(self._qs):
                    if done[i]:
                        batches.append(self.streams[i]._empty())
                        continue
                    item = self._get(q)
                    if item is _END:
                        done[i] = True
                        batches.append(self.streams[i]._empty())
                    else:
                        batches.append(item)
                if all(done):
                    break
                prepared = self.prepare(batches)
                if self.group_size == 1:
                    if not self._put(self._out, prepared):
                        return
                    continue
                pending.append(prepared)
                if len(pending) == self.group_size:
                    if not self._put(self._out, self.assemble(pending)):
                        return
                    pending = []
            if pending and not self._stop.is_set():
                # pad the final partial group with inert prepared items
                empty = self.prepare([s._empty() for s in self.streams])
                pending += [empty] * (self.group_size - len(pending))
                self._put(self._out, self.assemble(pending))
        except BaseException as e:
            self._errs.append(e)
        finally:
            self._put(self._out, _END)

    # -- consumer API ------------------------------------------------------
    def get(self):
        """Next ready step item; None once (and forever after) every
        stream has drained. Producer-thread exceptions re-raise here."""
        if self._errs:
            self._stop.set()
            raise self._errs[0]
        if self._drained:
            return None
        item = self._out.get()
        if item is _END:
            self._drained = True
            if self._errs:
                raise self._errs[0]
            return None
        return item

    def close(self) -> None:
        """Unstick and retire all threads (safe to call twice)."""
        self._stop.set()
        for q in [*self._qs, self._out]:
            try:
                q.get_nowait()
            except queue.Empty:
                pass
        for t in self._threads:
            t.join(timeout=5)

    def __enter__(self) -> "PrefetchPipeline":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
