"""libsvm / criteo text parsers — Python reference implementations.

Reference analog: src/data/text_parser.cc (libsvm, criteo, adfea formats,
slot-aware). The C++ fast path lives in native/parser.cpp and must produce
bit-identical output (same hashing; see utils.hashing). This module is the
correctness reference and the fallback when the extension isn't built.
"""

from __future__ import annotations

import gzip
from collections.abc import Iterator
from pathlib import Path

import numpy as np

Row = tuple[float, np.ndarray, np.ndarray, np.ndarray]  # label, keys, vals, slots


def _open(path: str | Path):
    p = Path(path)
    if p.suffix == ".gz":
        return gzip.open(p, "rt")
    return p.open("r")


def iter_libsvm(path: str | Path) -> Iterator[Row]:
    """Parse ``label idx:val idx:val ...``; labels -1/0/+1 -> 0/1.

    Ref: ParseLibsvm in src/data/text_parser.cc. Slot id is 0 for all
    features (libsvm has no feature groups).
    """
    with _open(path) as f:
        for line in f:
            parts = line.split()
            if not parts:
                continue
            label = 1.0 if float(parts[0]) > 0 else 0.0
            n = len(parts) - 1
            keys = np.empty(n, dtype=np.uint64)
            vals = np.empty(n, dtype=np.float32)
            for i, tok in enumerate(parts[1:]):
                k, _, v = tok.partition(":")
                keys[i] = int(k)
                vals[i] = float(v) if v else 1.0
            yield label, keys, vals, np.zeros(n, dtype=np.uint64)


def iter_criteo(path: str | Path) -> Iterator[Row]:
    """Parse Criteo CTR TSV: label, 13 integer slots, 26 categorical slots.

    Ref: ParseCriteo in src/data/text_parser.cc. Integer slot j becomes key
    ``raw value`` in slot j+1; categorical slot j becomes its hex id in slot
    j+14 — the slot salt keeps columns decorrelated in the hashed space.
    Missing fields are skipped (reference behavior).
    """
    with _open(path) as f:
        for line in f:
            cols = line.rstrip("\n").split("\t")
            if len(cols) < 40:
                continue
            label = 1.0 if cols[0] == "1" else 0.0
            keys, vals, slots = [], [], []
            for j in range(13):  # integer features: log-ish value encoding
                c = cols[1 + j]
                try:
                    x = int(c)
                except ValueError:
                    continue  # malformed fields are skipped (ref behavior)
                keys.append(j)  # one weight per integer column...
                vals.append(np.sign(x) * np.log1p(abs(x)))  # ...scaled by value
                slots.append(j + 1)
            for j in range(26):  # categorical: one-hot by hashed id
                c = cols[14 + j]
                if c == "":
                    continue
                try:
                    k = int(c, 16)
                except ValueError:
                    continue
                keys.append(k)
                vals.append(1.0)
                slots.append(j + 14)
            n = len(keys)
            yield (
                label,
                np.array(keys, dtype=np.uint64),
                np.array(vals, dtype=np.float32),
                np.array(slots, dtype=np.uint64),
            )


def iter_adfea(path: str | Path) -> Iterator[Row]:
    """Parse the adfea ad-feature format: ``line_id label fea:grp fea:grp ...``.

    Ref: ParseAdfea in src/data/text_parser.cc. Each token after the line id
    and click label is ``feature_id:group_id``; the group id is the slot
    (feature group) and the value is implicitly 1.0 (pure one-hot ad
    features). A token without ``:`` gets slot 0. The leading line id is
    metadata and is dropped.
    """
    with _open(path) as f:
        for line in f:
            parts = line.split()
            if len(parts) < 2:
                continue
            label = 1.0 if float(parts[1]) > 0 else 0.0
            n = len(parts) - 2
            keys = np.empty(n, dtype=np.uint64)
            slots = np.zeros(n, dtype=np.uint64)
            for i, tok in enumerate(parts[2:]):
                k, _, g = tok.partition(":")
                keys[i] = int(k)
                if g:
                    slots[i] = int(g)
            yield label, keys, np.ones(n, dtype=np.float32), slots


FORMATS = {"libsvm": iter_libsvm, "criteo": iter_criteo, "adfea": iter_adfea}


def iter_format(fmt: str, path: str | Path) -> Iterator[Row]:
    if fmt not in FORMATS:
        raise ValueError(f"unknown data format {fmt!r}; known: {sorted(FORMATS)}")
    return FORMATS[fmt](path)
