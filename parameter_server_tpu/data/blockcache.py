"""Columnar feature-block layout + disk cache for the batch solver.

Reference analog: src/data/slot_reader.h/.cc — the reference's SlotReader
parses the training text once and caches per-slot column blocks as binary
files in a local cache dir; later passes (and re-runs) read the cache
instead of re-parsing. Same contract here:

  - ``ColumnBlocks`` is the feature-major (CSC-ish) layout the DARLIN
    solver sweeps: entries grouped by contiguous dense-key block, padded to
    a static per-block width so one ``lax.scan`` covers every block.
  - ``save_column_blocks`` / ``load_column_blocks`` persist the arrays as
    ``.npy`` files plus a ``meta.json`` stats sidecar carrying a source
    fingerprint (file paths, sizes, mtimes, parse parameters). Loads are
    ``mmap_mode="r"`` so a reload never re-parses text and only pages in
    what a pass touches.
  - ``cached_column_blocks`` orchestrates: fingerprint-hit -> mmap load;
    miss (or no cache dir) -> parse + build + save.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass
from pathlib import Path

import numpy as np

from parameter_server_tpu.data.batch import CSRBatch

CACHE_VERSION = 1
_ARRAYS = ("feat_local", "rows", "values", "labels")


@dataclass
class ColumnBlocks:
    """Feature-major (CSC-ish) layout of the full training set.

    Entries are grouped by feature block (contiguous ranges of the dense
    key space — the reference picks blocks from slots/feature groups; dense
    hashed ranges are the TPU analog), padded per block to a common length
    so a scan can sweep blocks with static shapes. Padding entries point at
    local feature 0 / row 0 with value 0 (inert, as everywhere else)."""

    feat_local: np.ndarray  # (n_blocks, E) int32 — gid - block_begin
    rows: np.ndarray  # (n_blocks, E) int32
    values: np.ndarray  # (n_blocks, E) float32
    labels: np.ndarray  # (N,) float32
    num_keys: int
    block_size: int
    num_examples: int

    @property
    def n_blocks(self) -> int:
        return len(self.feat_local)

    @classmethod
    def from_batches(
        cls, batches: list[CSRBatch], num_keys: int, n_blocks: int
    ) -> "ColumnBlocks":
        """Build from CSRBatches (uses their global hashed unique_keys)."""
        if num_keys % n_blocks:
            raise ValueError(f"num_keys {num_keys} % n_blocks {n_blocks} != 0")
        gids, rows, vals, labels = [], [], [], []
        row0 = 0
        for b in batches:
            n, e = b.num_examples, b.num_entries
            gids.append(b.unique_keys[b.local_ids[:e]])
            rows.append(b.row_ids[:e].astype(np.int64) + row0)
            vals.append(b.values[:e])
            labels.append(b.labels[:n])
            row0 += n
        gid = np.concatenate(gids)
        row = np.concatenate(rows)
        val = np.concatenate(vals)
        y = np.concatenate(labels)

        block_size = num_keys // n_blocks
        blk = (gid // block_size).astype(np.int64)
        order = np.argsort(blk, kind="stable")
        gid, row, val, blk = gid[order], row[order], val[order], blk[order]
        counts = np.bincount(blk, minlength=n_blocks)
        e_max = max(1, int(counts.max()))
        feat_local = np.zeros((n_blocks, e_max), dtype=np.int32)
        rows_out = np.zeros((n_blocks, e_max), dtype=np.int32)
        vals_out = np.zeros((n_blocks, e_max), dtype=np.float32)
        starts = np.concatenate([[0], np.cumsum(counts)])
        for i in range(n_blocks):
            s, e = starts[i], starts[i + 1]
            c = e - s
            feat_local[i, :c] = gid[s:e] - i * block_size
            rows_out[i, :c] = row[s:e]
            vals_out[i, :c] = val[s:e]
        return cls(
            feat_local=feat_local,
            rows=rows_out,
            values=vals_out,
            labels=y,
            num_keys=num_keys,
            block_size=block_size,
            num_examples=len(y),
        )


def source_fingerprint(
    files: list[str],
    fmt: str,
    num_keys: int,
    n_blocks: int,
    max_nnz_per_example: int,
) -> str:
    """Hash of everything that determines the cache contents: source file
    identities (path, size, mtime) + the parse/layout parameters."""
    ident = {
        "version": CACHE_VERSION,
        "fmt": fmt,
        "num_keys": num_keys,
        "n_blocks": n_blocks,
        "max_nnz": max_nnz_per_example,
        "files": [],
    }
    for f in sorted(map(str, files)):
        st = Path(f).stat()  # missing source files are a hard error
        ident["files"].append([f, st.st_size, st.st_mtime_ns])
    return hashlib.sha256(json.dumps(ident).encode()).hexdigest()


def save_column_blocks(cache_dir: str | Path, cb: ColumnBlocks, fingerprint: str) -> None:
    d = Path(cache_dir)
    d.mkdir(parents=True, exist_ok=True)
    # invalidate any previous cache before touching the arrays, so a crash
    # mid-write can never leave a valid-looking sidecar over mixed contents
    (d / "meta.json").unlink(missing_ok=True)
    for name in _ARRAYS:
        np.save(d / f"{name}.npy", getattr(cb, name))
    meta = {
        "version": CACHE_VERSION,
        "fingerprint": fingerprint,
        "num_keys": cb.num_keys,
        "block_size": cb.block_size,
        "num_examples": cb.num_examples,
        "n_blocks": cb.n_blocks,
        "nnz": int((cb.values != 0).sum()),
    }
    # sidecar written last and atomically: its presence marks a complete
    # cache, so a partial write must never be observable at the final path
    tmp = d / "meta.json.tmp"
    tmp.write_text(json.dumps(meta, indent=1))
    os.replace(tmp, d / "meta.json")


def load_column_blocks(
    cache_dir: str | Path, fingerprint: str | None = None
) -> ColumnBlocks | None:
    """mmap-load a cache; None when absent, incomplete, or stale."""
    d = Path(cache_dir)
    meta_path = d / "meta.json"
    if not meta_path.exists():
        return None
    try:
        meta = json.loads(meta_path.read_text())
        if meta.get("version") != CACHE_VERSION:
            return None
        if fingerprint is not None and meta.get("fingerprint") != fingerprint:
            return None
        arrays = {}
        for name in _ARRAYS:
            p = d / f"{name}.npy"
            if not p.exists():
                return None
            arrays[name] = np.load(p, mmap_mode="r")
        return ColumnBlocks(
            **arrays,
            num_keys=meta["num_keys"],
            block_size=meta["block_size"],
            num_examples=meta["num_examples"],
        )
    except (json.JSONDecodeError, KeyError, ValueError, OSError):
        return None  # corrupt/truncated cache == cache miss, rebuild it


def cached_column_blocks(cfg) -> ColumnBlocks:
    """SlotReader behavior for a PSConfig: reuse ``data.cache_dir`` when its
    fingerprint matches the sources, else parse once and populate it."""
    from parameter_server_tpu.data.batch import BatchBuilder
    from parameter_server_tpu.data.reader import MinibatchReader

    n_blocks = cfg.solver.feature_blocks
    fp = source_fingerprint(
        cfg.data.files,
        cfg.data.format,
        cfg.data.num_keys,
        n_blocks,
        cfg.data.max_nnz_per_example,
    )
    if cfg.data.cache_dir:
        cb = load_column_blocks(cfg.data.cache_dir, fp)
        if cb is not None:
            return cb
    builder = BatchBuilder(
        num_keys=cfg.data.num_keys,
        batch_size=cfg.solver.minibatch,
        max_nnz_per_example=cfg.data.max_nnz_per_example,
    )
    batches = list(MinibatchReader(cfg.data.files, cfg.data.format, builder))
    cb = ColumnBlocks.from_batches(batches, cfg.data.num_keys, n_blocks)
    if cfg.data.cache_dir:
        save_column_blocks(cfg.data.cache_dir, cb, fp)
    return cb
