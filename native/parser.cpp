// Native text parsers: libsvm + criteo -> flat CSR arrays.
//
// Reference analog: src/data/text_parser.cc (the reference parses libsvm /
// criteo / adfea into slot-based Example protos in C++; parsing is a real
// hot path at CTR scale). This extension keeps that path native: it turns a
// chunk of complete text lines into flat (labels, row_splits, keys, vals,
// slots) arrays consumed zero-copy by numpy via ctypes.
//
// Contract notes:
//  - Caller passes a buffer of COMPLETE lines (the Python wrapper carries
//    partial tails between chunks).
//  - Outputs are caller-allocated; capacities passed in. Return value is 0
//    on success, -1 on capacity overflow, -2 on parse error (err_line gets
//    the 0-based index of the offending line in the chunk).
//  - Key hashing stays on the numpy side (utils.hashing) so Python and C++
//    ingest agree bit-for-bit by construction.
//  - ``slots`` may be NULL for slot-free formats (libsvm): the parser then
//    skips the per-entry zero store and the caller skips the buffer.

#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {

// fast positive-integer / hex parse; returns false on junk.
// (plain range compares, not std::isdigit: the locale-aware function
// call is a measurable cost in the per-entry hot loop)
inline bool is_digit(char c) { return c >= '0' && c <= '9'; }

inline bool parse_u64(const char*& p, const char* end, uint64_t& out) {
  if (p >= end || !is_digit(*p)) return false;
  uint64_t v = 0;
  while (p < end && is_digit(*p)) {
    v = v * 10 + static_cast<uint64_t>(*p - '0');
    ++p;
  }
  out = v;
  return true;
}

inline bool parse_hex64(const char*& p, const char* end, uint64_t& out) {
  uint64_t v = 0;
  const char* start = p;
  while (p < end) {
    char c = *p;
    int d;
    if (c >= '0' && c <= '9') d = c - '0';
    else if (c >= 'a' && c <= 'f') d = c - 'a' + 10;
    else if (c >= 'A' && c <= 'F') d = c - 'A' + 10;
    else break;
    v = (v << 4) | static_cast<uint64_t>(d);
    ++p;
  }
  if (p == start) return false;
  out = v;
  return true;
}

inline double parse_float_slow(const char*& p, const char* end) {
  // strtod needs a NUL-terminated-ish region; lines are short, copy-free use
  // is fine because strtod stops at the first invalid char and the buffer
  // always ends with '\n' (guaranteed by the wrapper).
  char* q = nullptr;
  double v = std::strtod(p, &q);
  p = (q && q <= end) ? q : p;
  return v;
}

inline double parse_float(const char*& p, const char* end) {
  // Exact fast path for plain decimals (the overwhelming case in ML text
  // formats): when the collected mantissa fits in 53 bits and the decimal
  // exponent is within +/-22, one double multiply/divide by an exactly-
  // representable power of ten is CORRECTLY ROUNDED — bit-identical to
  // strtod (and hence to the Python parsers). Everything else (inf/nan,
  // hex floats, 19+ significant digits, big exponents) falls back to
  // strtod, reparsing from the start so consumption always matches.
  static const double P10[23] = {
      1.0, 1e1, 1e2, 1e3, 1e4, 1e5, 1e6, 1e7, 1e8, 1e9, 1e10, 1e11,
      1e12, 1e13, 1e14, 1e15, 1e16, 1e17, 1e18, 1e19, 1e20, 1e21, 1e22};
  const char* s = p;
  bool neg = false;
  if (s < end && (*s == '-' || *s == '+')) {
    neg = (*s == '-');
    ++s;
  }
  uint64_t mant = 0;
  int ndig = 0, exp10 = 0;
  bool any = false, inexact = false;
  while (s < end && *s >= '0' && *s <= '9') {
    any = true;
    if (ndig < 19) {
      mant = mant * 10 + static_cast<uint64_t>(*s - '0');
      ++ndig;
    } else {
      ++exp10;  // dropped trailing integer digit
      inexact = true;
    }
    ++s;
  }
  if (s < end && *s == '.') {
    ++s;
    while (s < end && *s >= '0' && *s <= '9') {
      any = true;
      if (ndig < 19) {
        mant = mant * 10 + static_cast<uint64_t>(*s - '0');
        ++ndig;
        --exp10;
      } else {
        inexact = true;  // dropped fraction digit
      }
      ++s;
    }
  }
  if (!any) return parse_float_slow(p, end);  // inf/nan/junk: strtod rules
  // C99 hex floats ("0x1Ap-3"): the leading 0 scanned as decimal; detect
  // the x/X and let strtod parse (and consume) the whole literal
  if (mant == 0 && s < end && (*s == 'x' || *s == 'X'))
    return parse_float_slow(p, end);
  if (s < end && (*s == 'e' || *s == 'E')) {
    const char* es = s + 1;
    bool eneg = false;
    if (es < end && (*es == '-' || *es == '+')) {
      eneg = (*es == '-');
      ++es;
    }
    int ev = 0;
    bool edig = false;
    while (es < end && *es >= '0' && *es <= '9' && ev < 10000) {
      ev = ev * 10 + (*es - '0');
      edig = true;
      ++es;
    }
    if (edig) {
      exp10 += eneg ? -ev : ev;
      s = es;
    }
    // 'e' with no digits: the number ends before 'e' (strtod agrees)
  }
  if (!inexact && mant < (1ull << 53) && exp10 >= -22 && exp10 <= 22) {
    double v = static_cast<double>(mant);
    v = exp10 >= 0 ? v * P10[exp10] : v / P10[-exp10];
    p = s;
    return neg ? -v : v;
  }
  return parse_float_slow(p, end);
}

inline void skip_ws(const char*& p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t')) ++p;
}

// Line end for [p, buf_end): first '\n', '\r', or '\r\n' terminator (or
// buf_end), universal-newlines style, so CRLF and lone-CR files parse like
// the Python text-mode readers. ``any_cr`` is a chunk-level hint computed
// ONCE (one memchr over the chunk): the overwhelmingly common LF-only
// file skips the per-line '\r' scan — a second full pass over every
// line's bytes otherwise.
inline const char* find_line_end(const char* p, const char* end,
                                 const char** next_line, bool any_cr) {
  const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
  if (any_cr) {
    // search '\r' only up to nl: scanning to end on every LF-only line
    // would make parsing quadratic in the chunk size
    const char* cr_stop = nl ? nl : end;
    const char* cr = static_cast<const char*>(memchr(p, '\r', cr_stop - p));
    if (cr) {
      *next_line = (cr + 1 < end && cr[1] == '\n') ? cr + 2 : cr + 1;
      return cr;
    }
  }
  *next_line = nl ? nl + 1 : end + 1;
  return nl ? nl : end;
}

inline bool chunk_has_cr(const char* buf, int64_t len) {
  return memchr(buf, '\r', len) != nullptr;
}

}  // namespace

extern "C" {

// libsvm: "label k:v k:v ...". Labels <= 0 -> 0, > 0 -> 1. Slot = 0.
int ps_parse_libsvm(const char* buf, int64_t len,
                    int64_t max_rows, int64_t max_nnz,
                    float* labels, int64_t* row_splits,  // size max_rows+1
                    uint64_t* keys, float* vals, uint64_t* slots,
                    int64_t* out_rows, int64_t* out_nnz, int64_t* err_line) {
  const char* p = buf;
  const char* end = buf + len;
  const bool any_cr = chunk_has_cr(buf, len);
  int64_t rows = 0, nnz = 0, line = 0;
  row_splits[0] = 0;
  while (p < end) {
    const char* next_line;
    const char* line_end = find_line_end(p, end, &next_line, any_cr);
    skip_ws(p, line_end);
    if (p >= line_end) {  // blank line
      p = next_line;
      ++line;
      continue;
    }
    if (rows >= max_rows) return -1;
    double y = parse_float(p, line_end);
    labels[rows] = y > 0 ? 1.0f : 0.0f;
    while (true) {
      skip_ws(p, line_end);
      if (p >= line_end) break;
      uint64_t k;
      if (!parse_u64(p, line_end, k)) {
        *err_line = line;
        return -2;
      }
      float v = 1.0f;
      if (p < line_end && *p == ':') {
        ++p;
        // empty value ("k:" then whitespace/EOL) means 1.0, like the Python
        // parser; never let strtod skip leading whitespace across the EOL
        if (p < line_end && *p != ' ' && *p != '\t') {
          v = static_cast<float>(parse_float(p, line_end));
        }
      }
      if (nnz >= max_nnz) return -1;
      keys[nnz] = k;
      vals[nnz] = v;
      if (slots) slots[nnz] = 0;  // null for slotless callers
      ++nnz;
    }
    ++rows;
    row_splits[rows] = nnz;
    p = next_line;
    ++line;
  }
  *out_rows = rows;
  *out_nnz = nnz;
  return 0;
}

// criteo TSV: label \t 13 ints \t 26 hex cats. Missing fields skipped.
// Integer column j -> key j, slot j+1, value sign*log1p(|x|);
// categorical column j -> key hex id, slot j+14, value 1.0.
int ps_parse_criteo(const char* buf, int64_t len,
                    int64_t max_rows, int64_t max_nnz,
                    float* labels, int64_t* row_splits,
                    uint64_t* keys, float* vals, uint64_t* slots,
                    int64_t* out_rows, int64_t* out_nnz, int64_t* err_line) {
  (void)err_line;  // criteo skips malformed lines instead of erroring
  const char* p = buf;
  const char* end = buf + len;
  const bool any_cr = chunk_has_cr(buf, len);
  int64_t rows = 0, nnz = 0, line = 0;
  row_splits[0] = 0;
  while (p < end) {
    const char* next_line;
    const char* line_end = find_line_end(p, end, &next_line, any_cr);
    if (p >= line_end) {
      p = next_line;
      ++line;
      continue;
    }
    // count fields first: need 40 columns; otherwise skip the line
    int cols = 1;
    for (const char* q = p; q < line_end; ++q)
      if (*q == '\t') ++cols;
    if (cols < 40) {
      p = next_line;
      ++line;
      continue;
    }
    if (rows >= max_rows) return -1;
    labels[rows] = (*p == '1' && (p + 1 == line_end || p[1] == '\t')) ? 1.0f : 0.0f;
    const char* f = static_cast<const char*>(memchr(p, '\t', line_end - p));
    int col = 0;  // 0-based among the 39 feature columns
    while (f && col < 39) {
      ++f;  // past the tab
      const char* fe = static_cast<const char*>(memchr(f, '\t', line_end - f));
      const char* field_end = fe ? fe : line_end;
      if (field_end > f) {  // non-empty
        if (nnz >= max_nnz) return -1;
        if (col < 13) {
          const char* fp = f;
          bool neg = (*fp == '-');
          if (neg) ++fp;
          uint64_t x;
          // require the WHOLE field to parse: junk like "3x7" is skipped,
          // never truncated to a prefix (both ingest paths agree on this)
          if (parse_u64(fp, field_end, x) && fp == field_end) {
            double lx = std::log1p(static_cast<double>(x));
            keys[nnz] = static_cast<uint64_t>(col);
            vals[nnz] = static_cast<float>(neg ? -lx : lx);
            slots[nnz] = static_cast<uint64_t>(col + 1);
            ++nnz;
          }
        } else {
          const char* fp = f;
          uint64_t h;
          if (parse_hex64(fp, field_end, h) && fp == field_end) {
            keys[nnz] = h;
            vals[nnz] = 1.0f;
            slots[nnz] = static_cast<uint64_t>(col - 13 + 14);
            ++nnz;
          }
        }
      }
      ++col;
      f = fe;
    }
    ++rows;
    row_splits[rows] = nnz;
    p = next_line;
    ++line;
  }
  *out_rows = rows;
  *out_nnz = nnz;
  return 0;
}

// Hash + localize kernel (ref: src/app/linear_method/localizer.h — remap
// touched keys to dense local ids; the per-batch hot loop after parsing).
// Reproduces utils/hashing.hash_keys + np.unique(return_inverse) exactly:
// splitmix64 with slot salt into [1, num_keys), then SORTED unique keys +
// 0-based inverse ids. Runs with the GIL released (ctypes), so the
// prefetch pipeline's builder threads scale across cores — numpy's
// unique/hash hold the GIL and serialize them.
//
// identity != 0 skips hashing: gid = raw + 1 (the exact-parity key mode).
// Sorting: 2-pass LSD radix over the high 32 bits of (gid<<32 | idx),
// which requires gid to fit 32 bits (num_keys <= 2^32 — practically
// always). Return codes: 0 success; -3 identity gid outside
// [1, num_keys); -4 alloc failure; -5 num_keys > 2^32. On -3/-5 the
// caller falls back to the numpy path (which owns the error text for -3
// and handles arbitrarily large key spaces for -5).

static inline uint64_t sm64_mix(uint64_t x) {
  // identical constants/steps to utils/hashing.splitmix64 (which adds C1
  // as its first step)
  uint64_t z = x + 0x9E3779B97F4A7C15ull;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
  return z ^ (z >> 31);
}

int ps_hash_localize(const uint64_t* raw, const uint64_t* slots, int64_t n,
                     uint64_t num_keys, int identity,
                     int64_t* out_unique, int32_t* out_inverse,
                     int64_t* out_nuniq) {
  if (n == 0) {
    *out_nuniq = 0;
    return 0;
  }
  uint64_t* packed =
      static_cast<uint64_t*>(std::malloc(2 * sizeof(uint64_t) * n));
  if (!packed) return -4;
  uint64_t* alt = packed + n;
  const uint64_t usable = num_keys - 1;  // hashed gids land in [1, num_keys)
  const uint64_t C1 = 0x9E3779B97F4A7C15ull;
  if (identity) {
    for (int64_t i = 0; i < n; ++i) {
      uint64_t gid = raw[i] + 1;
      if (gid >= num_keys || gid == 0) {
        std::free(packed);
        return -3;
      }
      packed[i] = (gid << 32) | static_cast<uint64_t>(i);
    }
  } else if (slots) {
    for (int64_t i = 0; i < n; ++i) {
      uint64_t gid = sm64_mix(raw[i] ^ sm64_mix(slots[i] + C1)) % usable + 1;
      packed[i] = (gid << 32) | static_cast<uint64_t>(i);
    }
  } else {
    const uint64_t salt0 = sm64_mix(C1);  // slot 0 salt, hoisted
    for (int64_t i = 0; i < n; ++i) {
      uint64_t gid = sm64_mix(raw[i] ^ salt0) % usable + 1;
      packed[i] = (gid << 32) | static_cast<uint64_t>(i);
    }
  }
  if (num_keys <= (1ull << 32) && n < (int64_t(1) << 32)) {
    // stable LSD radix over gid bits only (low idx bits untouched, so
    // equal gids keep insertion order, like a stable sort). The count
    // table lives on the heap: builder threads may carry small stacks
    // (512 KB default pthread stacks on some platforms).
    int64_t* count =
        static_cast<int64_t*>(std::malloc(65537 * sizeof(int64_t)));
    if (!count) {
      std::free(packed < alt ? packed : alt);
      return -4;
    }
    for (int pass = 0; pass < 2; ++pass) {
      int shift = 32 + 16 * pass;
      std::memset(count, 0, 65537 * sizeof(int64_t));
      for (int64_t i = 0; i < n; ++i)
        ++count[((packed[i] >> shift) & 0xffff) + 1];
      for (int b = 0; b < 65536; ++b) count[b + 1] += count[b];
      for (int64_t i = 0; i < n; ++i)
        alt[count[(packed[i] >> shift) & 0xffff]++] = packed[i];
      uint64_t* t = packed;
      packed = alt;
      alt = t;
    }
    std::free(count);
  } else {
    // gid may exceed 32 bits: the (gid<<32 | idx) pack is lossy there
    std::free(packed);
    return -5;  // caller falls back to numpy (num_keys > 2^32)
  }
  int64_t u = 0;
  uint64_t prev = ~0ull;
  for (int64_t i = 0; i < n; ++i) {
    uint64_t gid = packed[i] >> 32;
    uint32_t idx = static_cast<uint32_t>(packed[i]);
    if (gid != prev) {
      out_unique[u++] = static_cast<int64_t>(gid);
      prev = gid;
    }
    out_inverse[idx] = static_cast<int32_t>(u - 1);
  }
  *out_nuniq = u;
  // note: `packed` here may be the original malloc block or its second
  // half; free the block start
  std::free(packed < alt ? packed : alt);
  return 0;
}

// adfea: "line_id label fea:grp fea:grp ...". Pure one-hot ad features:
// value is implicitly 1.0, the group id is the slot. Leading line id is
// metadata and dropped WITHOUT being parsed (ids like hashes are fine,
// matching the Python path). A token without ':' gets slot 0.
int ps_parse_adfea(const char* buf, int64_t len,
                   int64_t max_rows, int64_t max_nnz,
                   float* labels, int64_t* row_splits,
                   uint64_t* keys, float* vals, uint64_t* slots,
                   int64_t* out_rows, int64_t* out_nnz, int64_t* err_line) {
  const char* p = buf;
  const char* end = buf + len;
  const bool any_cr = chunk_has_cr(buf, len);
  int64_t rows = 0, nnz = 0, line = 0;
  row_splits[0] = 0;
  while (p < end) {
    const char* next_line;
    const char* line_end = find_line_end(p, end, &next_line, any_cr);
    skip_ws(p, line_end);
    if (p >= line_end) {  // blank line
      p = next_line;
      ++line;
      continue;
    }
    if (rows >= max_rows) return -1;
    while (p < line_end && *p != ' ' && *p != '\t') ++p;  // drop line id token
    skip_ws(p, line_end);
    if (p >= line_end) {  // line id but no label: skip, like the Python path
      p = next_line;
      ++line;
      continue;
    }
    // label must be a full float token (Python float() raises on junk)
    const char* tok = p;
    double y = parse_float(p, line_end);
    if (p == tok || (p < line_end && *p != ' ' && *p != '\t')) {
      *err_line = line;
      return -2;
    }
    labels[rows] = y > 0 ? 1.0f : 0.0f;
    while (true) {
      skip_ws(p, line_end);
      if (p >= line_end) break;
      uint64_t k;
      if (!parse_u64(p, line_end, k)) {
        *err_line = line;
        return -2;
      }
      uint64_t g = 0;
      if (p < line_end && *p == ':') {
        ++p;
        // "k:" with empty group -> slot 0, like Python's `if g:` guard
        if (p < line_end && *p != ' ' && *p != '\t' &&
            !parse_u64(p, line_end, g)) {
          *err_line = line;
          return -2;
        }
      }
      if (nnz >= max_nnz) return -1;
      keys[nnz] = k;
      vals[nnz] = 1.0f;
      slots[nnz] = g;
      ++nnz;
    }
    ++rows;
    row_splits[rows] = nnz;
    p = next_line;
    ++line;
  }
  *out_rows = rows;
  *out_nnz = nnz;
  return 0;
}

}  // extern "C"
