// Native text parsers: libsvm + criteo -> flat CSR arrays.
//
// Reference analog: src/data/text_parser.cc (the reference parses libsvm /
// criteo / adfea into slot-based Example protos in C++; parsing is a real
// hot path at CTR scale). This extension keeps that path native: it turns a
// chunk of complete text lines into flat (labels, row_splits, keys, vals,
// slots) arrays consumed zero-copy by numpy via ctypes.
//
// Contract notes:
//  - Caller passes a buffer of COMPLETE lines (the Python wrapper carries
//    partial tails between chunks).
//  - Outputs are caller-allocated; capacities passed in. Return value is 0
//    on success, -1 on capacity overflow, -2 on parse error (err_line gets
//    the 0-based index of the offending line in the chunk).
//  - Key hashing stays on the numpy side (utils.hashing) so Python and C++
//    ingest agree bit-for-bit by construction.
//  - ``slots`` may be NULL for slot-free formats (libsvm): the parser then
//    skips the per-entry zero store and the caller skips the buffer.

#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>

#if defined(__AVX2__)
#include <immintrin.h>
#endif

namespace {

// fast positive-integer / hex parse; returns false on junk.
// (plain range compares, not std::isdigit: the locale-aware function
// call is a measurable cost in the per-entry hot loop)
inline bool is_digit(char c) { return c >= '0' && c <= '9'; }

// ---- SWAR digit-run parsing (the classic 8-digits-per-multiply trick,
// as in fast_float/simdjson — public-domain bit patterns). The per-entry
// digit loops are the parser's hot path at CTR scale; converting up to 8
// digits with three multiplies instead of eight loop iterations is the
// single biggest lever toward the >=GB/s/host ingest target.

inline uint64_t load8(const char* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;
}

// number of LEADING decimal-digit bytes in the 8 loaded chars (0..8).
// Conservative under cross-byte carries (can only under-count, never
// call a non-digit a digit), so a short count just means the per-digit
// tail loop finishes the run — correctness never depends on it.
inline int leading_digits(uint64_t v) {
  uint64_t t =
      (((v & 0xF0F0F0F0F0F0F0F0ull) |
        (((v + 0x0606060606060606ull) & 0xF0F0F0F0F0F0F0F0ull) >> 4)) ^
       0x3333333333333333ull);
  return t ? __builtin_ctzll(t) >> 3 : 8;
}

// parse EXACTLY 8 digit bytes (first text char in the low byte) to their
// numeric value: pairwise digit merges via three multiplies
inline uint32_t swar8(uint64_t val) {
  val = (val & 0x0F0F0F0F0F0F0F0Full) * 2561 >> 8;
  val = (val & 0x00FF00FF00FF00FFull) * 6553601 >> 16;
  return static_cast<uint32_t>(
      (val & 0x0000FFFF0000FFFFull) * 42949672960001ull >> 32);
}

const uint64_t POW10_U64[9] = {1ull,      10ull,      100ull,
                               1000ull,   10000ull,   100000ull,
                               1000000ull, 10000000ull, 100000000ull};

// exactly-representable powers of ten for the correctly-rounded float
// fast path (shared by the bounded and sentinel parsers)
const double P10[23] = {
    1.0, 1e1, 1e2, 1e3, 1e4, 1e5, 1e6, 1e7, 1e8, 1e9, 1e10, 1e11,
    1e12, 1e13, 1e14, 1e15, 1e16, 1e17, 1e18, 1e19, 1e20, 1e21, 1e22};

// parse k (1..7) leading digits of the loaded chunk: shift them into the
// high bytes and pad the low bytes with ASCII zeros so swar8 sees a full
// 8-digit string "0...0 d0..d_{k-1}"
inline uint32_t swar_partial(uint64_t w, int k) {
  return swar8((w << ((8 - k) * 8)) | (0x3030303030303030ull >> (k * 8)));
}

inline bool parse_u64(const char*& p, const char* end, uint64_t& out) {
  if (p >= end || !is_digit(*p)) return false;
  uint64_t v = 0;
  // 8-digit SWAR chunks while a full load is in bounds. Wrap-around on
  // overlong runs matches the per-digit loop exactly: (v*10+d) mod 2^64
  // iterated k times == (v*10^k + chunk) mod 2^64.
  while (end - p >= 8) {
    uint64_t w = load8(p);
    int k = leading_digits(w);
    if (k == 0) break;
    if (k == 8) {
      v = v * 100000000ull + swar8(w);
      p += 8;
      continue;  // run may extend into the next 8 bytes
    }
    v = v * POW10_U64[k] + swar_partial(w, k);
    p += k;
    break;  // run ended at a non-digit
  }
  while (p < end && is_digit(*p)) {  // tail (near buffer end)
    v = v * 10 + static_cast<uint64_t>(*p - '0');
    ++p;
  }
  out = v;
  return true;
}

inline bool parse_hex64(const char*& p, const char* end, uint64_t& out) {
  uint64_t v = 0;
  const char* start = p;
  while (p < end) {
    char c = *p;
    int d;
    if (c >= '0' && c <= '9') d = c - '0';
    else if (c >= 'a' && c <= 'f') d = c - 'a' + 10;
    else if (c >= 'A' && c <= 'F') d = c - 'A' + 10;
    else break;
    v = (v << 4) | static_cast<uint64_t>(d);
    ++p;
  }
  if (p == start) return false;
  out = v;
  return true;
}

#if defined(__AVX2__)
// 8 hex chars -> uint32 in ~12 ops (vs 8 branchy loop iterations; the
// hex id parse is HALF of criteo parse time, measured). Validates with
// one SSE range check; nibble = (c & 0xF) + 9*(bit6 of c), which maps
// '0'-'9' / 'a'-'f' / 'A'-'F' without branches.
inline bool hex8(const char* p, uint32_t& out) {
  uint64_t w;
  std::memcpy(&w, p, 8);
  const __m128i v = _mm_cvtsi64_si128(static_cast<long long>(w));
  const __m128i lower = _mm_or_si128(v, _mm_set1_epi8(0x20));
  const __m128i dig = _mm_and_si128(
      _mm_cmpgt_epi8(v, _mm_set1_epi8('0' - 1)),
      _mm_cmpgt_epi8(_mm_set1_epi8('9' + 1), v));
  const __m128i alpha = _mm_and_si128(
      _mm_cmpgt_epi8(lower, _mm_set1_epi8('a' - 1)),
      _mm_cmpgt_epi8(_mm_set1_epi8('f' + 1), lower));
  if ((_mm_movemask_epi8(_mm_or_si128(dig, alpha)) & 0xFF) != 0xFF)
    return false;
  const uint64_t nib = (w & 0x0F0F0F0F0F0F0F0Full) +
                       9 * ((w >> 6) & 0x0101010101010101ull);
  const uint64_t t = ((nib << 4) | (nib >> 8)) & 0x00FF00FF00FF00FFull;
  out = static_cast<uint32_t>(((t & 0xFF) << 24) |
                              (((t >> 16) & 0xFF) << 16) |
                              (((t >> 32) & 0xFF) << 8) |
                              ((t >> 48) & 0xFF));
  return true;
}
#endif

inline double parse_float_slow(const char*& p, const char* end) {
  // strtod needs a NUL-terminated-ish region; lines are short, copy-free use
  // is fine because strtod stops at the first invalid char and the buffer
  // always ends with '\n' (guaranteed by the wrapper).
  char* q = nullptr;
  double v = std::strtod(p, &q);
  p = (q && q <= end) ? q : p;
  return v;
}

inline double parse_float(const char*& p, const char* end) {
  // Exact fast path for plain decimals (the overwhelming case in ML text
  // formats): when the collected mantissa fits in 53 bits and the decimal
  // exponent is within +/-22, one double multiply/divide by an exactly-
  // representable power of ten is CORRECTLY ROUNDED — bit-identical to
  // strtod (and hence to the Python parsers). Everything else (inf/nan,
  // hex floats, 19+ significant digits, big exponents) falls back to
  // strtod, reparsing from the start so consumption always matches.
  const char* s = p;
  bool neg = false;
  if (s < end && (*s == '-' || *s == '+')) {
    neg = (*s == '-');
    ++s;
  }
  uint64_t mant = 0;
  int ndig = 0, exp10 = 0;
  bool any = false, inexact = false;
  // integer part: SWAR chunks while they provably stay within the
  // 19-significant-digit budget; the per-digit loop finishes tails,
  // short runs, and the (rare) 19-digit boundary with the original
  // one-digit-at-a-time semantics
  while (end - s >= 8 && ndig + 8 <= 19) {
    uint64_t w = load8(s);
    int k = leading_digits(w);
    if (k == 0) break;
    any = true;
    mant = mant * POW10_U64[k] +
           (k == 8 ? swar8(w) : swar_partial(w, k));
    ndig += k;
    s += k;
    if (k < 8) break;  // run ended at a non-digit
  }
  while (s < end && *s >= '0' && *s <= '9') {
    any = true;
    if (ndig < 19) {
      mant = mant * 10 + static_cast<uint64_t>(*s - '0');
      ++ndig;
    } else {
      ++exp10;  // dropped trailing integer digit
      inexact = true;
    }
    ++s;
  }
  if (s < end && *s == '.') {
    ++s;
    while (end - s >= 8 && ndig + 8 <= 19) {
      uint64_t w = load8(s);
      int k = leading_digits(w);
      if (k == 0) break;
      any = true;
      mant = mant * POW10_U64[k] +
             (k == 8 ? swar8(w) : swar_partial(w, k));
      ndig += k;
      exp10 -= k;
      s += k;
      if (k < 8) break;
    }
    while (s < end && *s >= '0' && *s <= '9') {
      any = true;
      if (ndig < 19) {
        mant = mant * 10 + static_cast<uint64_t>(*s - '0');
        ++ndig;
        --exp10;
      } else {
        inexact = true;  // dropped fraction digit
      }
      ++s;
    }
  }
  if (!any) return parse_float_slow(p, end);  // inf/nan/junk: strtod rules
  // C99 hex floats ("0x1Ap-3"): the leading 0 scanned as decimal; detect
  // the x/X and let strtod parse (and consume) the whole literal
  if (mant == 0 && s < end && (*s == 'x' || *s == 'X'))
    return parse_float_slow(p, end);
  if (s < end && (*s == 'e' || *s == 'E')) {
    const char* es = s + 1;
    bool eneg = false;
    if (es < end && (*es == '-' || *es == '+')) {
      eneg = (*es == '-');
      ++es;
    }
    int ev = 0;
    bool edig = false;
    while (es < end && *es >= '0' && *es <= '9' && ev < 10000) {
      ev = ev * 10 + (*es - '0');
      edig = true;
      ++es;
    }
    if (edig) {
      exp10 += eneg ? -ev : ev;
      s = es;
    }
    // 'e' with no digits: the number ends before 'e' (strtod agrees)
  }
  if (!inexact && mant < (1ull << 53) && exp10 >= -22 && exp10 <= 22) {
    double v = static_cast<double>(mant);
    v = exp10 >= 0 ? v * P10[exp10] : v / P10[-exp10];
    p = s;
    return neg ? -v : v;
  }
  return parse_float_slow(p, end);
}

inline void skip_ws(const char*& p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t')) ++p;
}

// ---- sentinel-scanning variants. The wrapper guarantees the chunk's
// last byte is a line terminator, so whitespace/digit/number runs always
// stop at '\n' (or '\r') WITHOUT a per-byte end compare — that compare,
// plus the per-line memchr pass of find_line_end, is where the bounded
// parser spends a third of its time at CTR entry sizes. hard_end bounds
// only the 8-byte SWAR loads and the rare strtod fallback.

inline void skip_ws_nl(const char*& p) {
  while (*p == ' ' || *p == '\t') ++p;
}

inline bool parse_u64_nl(const char*& p, const char* hard_end,
                         uint64_t& out) {
  if (!is_digit(*p)) return false;
  uint64_t v = 0;
  while (hard_end - p >= 8) {
    uint64_t w = load8(p);
    int k = leading_digits(w);
    if (k == 0) break;
    if (k == 8) {
      v = v * 100000000ull + swar8(w);
      p += 8;
      continue;
    }
    v = v * POW10_U64[k] + swar_partial(w, k);
    p += k;
    break;
  }
  while (is_digit(*p)) {
    v = v * 10 + static_cast<uint64_t>(*p - '0');
    ++p;
  }
  out = v;
  return true;
}

inline double parse_float_nl(const char*& p, const char* hard_end) {
  // sentinel twin of parse_float (identical rounding semantics: exact
  // fast path or strtod fallback reparsing from the start)
  const char* s = p;
  bool neg = false;
  if (*s == '-' || *s == '+') {
    neg = (*s == '-');
    ++s;
  }
  uint64_t mant = 0;
  int ndig = 0, exp10 = 0;
  bool any = false, inexact = false;
  while (hard_end - s >= 8 && ndig + 8 <= 19) {
    uint64_t w = load8(s);
    int k = leading_digits(w);
    if (k == 0) break;
    any = true;
    mant = mant * POW10_U64[k] + (k == 8 ? swar8(w) : swar_partial(w, k));
    ndig += k;
    s += k;
    if (k < 8) break;
  }
  while (is_digit(*s)) {
    any = true;
    if (ndig < 19) {
      mant = mant * 10 + static_cast<uint64_t>(*s - '0');
      ++ndig;
    } else {
      ++exp10;
      inexact = true;
    }
    ++s;
  }
  if (*s == '.') {
    ++s;
    while (hard_end - s >= 8 && ndig + 8 <= 19) {
      uint64_t w = load8(s);
      int k = leading_digits(w);
      if (k == 0) break;
      any = true;
      mant = mant * POW10_U64[k] + (k == 8 ? swar8(w) : swar_partial(w, k));
      ndig += k;
      exp10 -= k;
      s += k;
      if (k < 8) break;
    }
    while (is_digit(*s)) {
      any = true;
      if (ndig < 19) {
        mant = mant * 10 + static_cast<uint64_t>(*s - '0');
        ++ndig;
        --exp10;
      } else {
        inexact = true;
      }
      ++s;
    }
  }
  if (!any) return parse_float_slow(p, hard_end);
  if (mant == 0 && (*s == 'x' || *s == 'X'))
    return parse_float_slow(p, hard_end);
  if (*s == 'e' || *s == 'E') {
    const char* es = s + 1;
    bool eneg = false;
    if (*es == '-' || *es == '+') {
      eneg = (*es == '-');
      ++es;
    }
    int ev = 0;
    bool edig = false;
    while (is_digit(*es) && ev < 10000) {
      ev = ev * 10 + (*es - '0');
      edig = true;
      ++es;
    }
    if (edig) {
      exp10 += eneg ? -ev : ev;
      s = es;
    }
  }
  if (!inexact && mant < (1ull << 53) && exp10 >= -22 && exp10 <= 22) {
    double v = static_cast<double>(mant);
    v = exp10 >= 0 ? v * P10[exp10] : v / P10[-exp10];
    p = s;
    return neg ? -v : v;
  }
  return parse_float_slow(p, hard_end);
}

// Line end for [p, buf_end): first '\n', '\r', or '\r\n' terminator (or
// buf_end), universal-newlines style, so CRLF and lone-CR files parse like
// the Python text-mode readers. ``any_cr`` is a chunk-level hint computed
// ONCE (one memchr over the chunk): the overwhelmingly common LF-only
// file skips the per-line '\r' scan — a second full pass over every
// line's bytes otherwise.
inline const char* find_line_end(const char* p, const char* end,
                                 const char** next_line, bool any_cr) {
  const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
  if (any_cr) {
    // search '\r' only up to nl: scanning to end on every LF-only line
    // would make parsing quadratic in the chunk size
    const char* cr_stop = nl ? nl : end;
    const char* cr = static_cast<const char*>(memchr(p, '\r', cr_stop - p));
    if (cr) {
      *next_line = (cr + 1 < end && cr[1] == '\n') ? cr + 2 : cr + 1;
      return cr;
    }
  }
  *next_line = nl ? nl + 1 : end + 1;
  return nl ? nl : end;
}

inline bool chunk_has_cr(const char* buf, int64_t len) {
  return memchr(buf, '\r', len) != nullptr;
}

#if defined(__AVX2__)
// value of the first k (0..8) digit bytes of a loaded chunk
inline uint64_t swar_prefix(uint64_t w, int k) {
  if (k == 8) return swar8(w);
  if (k == 0) return 0;
  return swar_partial(w, k);
}

// Parse a digit-only token span [q, te). The byte AT te is always a
// delimiter (non-digit), so leading_digits() self-terminates inside the
// span — one unguarded 8-byte load replaces the per-digit loop whenever
// q+8 stays in the buffer (q <= safe8). Rejects non-digit bytes inside
// the span; falls back to the per-digit loop for 9+ digit keys or
// end-of-buffer tokens.
inline bool parse_key_span(const char* q, const char* te, const char* safe8,
                           uint64_t& out) {
  const int64_t len = te - q;
  if (len <= 8 && q <= safe8) {
    uint64_t w = load8(q);
    if (leading_digits(w) < len) return false;
    out = swar_prefix(w, static_cast<int>(len));
    return true;
  }
  const char* p = q;
  if (!parse_u64(p, te, out)) return false;
  return p == te;
}

// Fast path for the overwhelming value/label shape [-+]?DDD(.DDD)? with
// <= 53-bit mantissa: two unguarded loads, no loop. Returns false (no
// consumption) on anything else — exponents, inf/nan, 17+ digits, hex,
// end-of-buffer spans — which the caller re-parses via the exact
// bounded parse_float. Correctly rounded for the same reason that path
// is: mant < 2^53, |exp10| <= 8 <= 22.
inline bool parse_val_span_fast(const char* q, const char* te,
                                const char* safe8, double& out) {
  const char* p = q;
  bool neg = false;
  if (p < te && (*p == '-' || *p == '+')) {
    neg = (*p == '-');
    ++p;
  }
  if (p >= te || p > safe8) return false;
  const uint64_t w = load8(p);
  const int k1 = leading_digits(w);  // stops at '.' or the end delimiter
  uint64_t mant = swar_prefix(w, k1);
  int ndig = k1, frac = 0;
  p += k1;
  if (p < te && *p == '.') {
    ++p;
    if (p > safe8) return false;
    const uint64_t w2 = load8(p);
    const int k2 = leading_digits(w2);
    mant = mant * POW10_U64[k2] + swar_prefix(w2, k2);
    ndig += k2;
    frac = k2;
    p += k2;
  }
  if (p != te || ndig == 0 || mant >= (1ull << 53)) return false;
  double v = static_cast<double>(mant);
  if (frac) v /= P10[frac];
  out = neg ? -v : v;
  return true;
}

// one 32-byte block -> bitmask of libsvm structural bytes (the token
// delimiters: ws, ':', line ends). simdjson-style stage-1 scan: the
// parser then touches only delimiter positions, never re-scanning token
// bytes — tokens are parsed from known [start, end) spans.
inline uint32_t delim_mask32(const char* p) {
  const __m256i c = _mm256_loadu_si256(reinterpret_cast<const __m256i*>(p));
  const __m256i m = _mm256_or_si256(
      _mm256_or_si256(
          _mm256_cmpeq_epi8(c, _mm256_set1_epi8(' ')),
          _mm256_cmpeq_epi8(c, _mm256_set1_epi8('\t'))),
      _mm256_or_si256(
          _mm256_cmpeq_epi8(c, _mm256_set1_epi8(':')),
          _mm256_or_si256(
              _mm256_cmpeq_epi8(c, _mm256_set1_epi8('\n')),
              _mm256_cmpeq_epi8(c, _mm256_set1_epi8('\r')))));
  return static_cast<uint32_t>(_mm256_movemask_epi8(m));
}

// AVX2 libsvm parser: delimiter-driven state machine over the structural
// bitmask (S_LABEL -> S_KEY <-> S_VALUE per line). Exactly the bounded
// parser's semantics, error lines included; ~2x over per-byte scanning
// at CTR entry sizes because work is per-DELIMITER (2-3 per entry), not
// per byte.
int ps_parse_libsvm_simd(const char* buf, int64_t len,
                         int64_t max_rows, int64_t max_nnz,
                         float* labels, int64_t* row_splits,
                         uint64_t* keys, float* vals, uint64_t* slots,
                         int64_t* out_rows, int64_t* out_nnz,
                         int64_t* err_line) {
  const char* end = buf + len;
  int64_t rows = 0, nnz = 0, line = 0;
  row_splits[0] = 0;
  if (len <= 0) {
    *out_rows = 0;
    *out_nnz = 0;
    return 0;
  }
  if (end[-1] != '\n' && end[-1] != '\r') return -6;  // closed-lines contract
  enum State { S_LABEL, S_KEY, S_VALUE };
  State st = S_LABEL;
  bool in_row = false;
  const char* ts = buf;  // current token start
  // spans starting at q <= safe8 may use one unguarded 8-byte load; the
  // handful of tokens in the final 8 bytes take the per-digit fallback
  const char* safe8 = end - 8;
  for (int64_t base = 0; base < len; base += 32) {
    uint32_t m;
    if (len - base >= 32) {
      m = delim_mask32(buf + base);
    } else {
      m = 0;
      for (int64_t i = base; i < len; ++i) {
        char c = buf[i];
        if (c == ' ' || c == '\t' || c == ':' || c == '\n' || c == '\r')
          m |= 1u << (i - base);
      }
    }
    while (m) {
      const int b = __builtin_ctz(m);
      m &= m - 1;
      const char* dp = buf + base + b;
      const char d = *dp;
      const char* te = dp;
      if (d == '\n' && dp > buf && dp[-1] == '\r') {
        ts = dp + 1;  // the LF of a CRLF: same line end, already handled
        continue;
      }
      if (d == ':') {
        // only a nonempty KEY token may end at ':' (a ':' at line start,
        // after a label, inside a value, or "::" is a parse error — the
        // per-byte parsers reject the same shapes)
        uint64_t k;
        if (st != S_KEY || ts == te || !parse_key_span(ts, te, safe8, k)) {
          *err_line = line;
          return -2;
        }
        if (nnz >= max_nnz) return -1;
        keys[nnz] = k;  // value lands at this same slot on the next token
        st = S_VALUE;
        ts = dp + 1;
        continue;
      }
      // d is ws or a line end: the token (possibly empty) is complete
      if (ts != te) {
        if (st == S_LABEL) {
          if (rows >= max_rows) return -1;
          double y;
          if (!parse_val_span_fast(ts, te, safe8, y)) {
            const char* q = ts;
            y = parse_float(q, te);
            if (q != te) {  // junk after the number: same error as per-byte
              *err_line = line;
              return -2;
            }
          }
          labels[rows] = y > 0 ? 1.0f : 0.0f;
          in_row = true;
          st = S_KEY;
        } else if (st == S_KEY) {  // bare key: implicit value 1.0
          uint64_t k;
          if (!parse_key_span(ts, te, safe8, k)) {
            *err_line = line;
            return -2;
          }
          if (nnz >= max_nnz) return -1;
          keys[nnz] = k;
          vals[nnz] = 1.0f;
          if (slots) slots[nnz] = 0;
          ++nnz;
        } else {  // S_VALUE
          double v;
          if (!parse_val_span_fast(ts, te, safe8, v)) {
            const char* q = ts;
            v = parse_float(q, te);
            if (q != te) {
              *err_line = line;
              return -2;
            }
          }
          vals[nnz] = static_cast<float>(v);
          if (slots) slots[nnz] = 0;
          ++nnz;
          st = S_KEY;
        }
      } else if (st == S_VALUE) {  // "k:" with empty value means 1.0
        vals[nnz] = 1.0f;
        if (slots) slots[nnz] = 0;
        ++nnz;
        st = S_KEY;
      }
      if (d == '\n' || d == '\r') {
        if (in_row) {
          ++rows;
          row_splits[rows] = nnz;
          in_row = false;
        }
        st = S_LABEL;
        ++line;
      }
      ts = dp + 1;
    }
  }
  *out_rows = rows;
  *out_nnz = nnz;
  return 0;
}
#endif  // __AVX2__

}  // namespace

extern "C" {

// count occurrences of up to four byte values in one pass (AVX2 compare
// + popcount; ~10 GB/s). The wrapper sizes its exact output arrays from
// newline/colon/ws counts — python's bytes.count pays per-occurrence
// overhead (~14 ns/hit measured), which at CTR colon densities costs
// more than the parse itself.
void ps_count4(const char* buf, int64_t len, char a, char b, char c, char d,
               int64_t* out) {
  int64_t ca = 0, cb = 0, cc = 0, cd = 0;
  int64_t i = 0;
#if defined(__AVX2__)
  const __m256i va = _mm256_set1_epi8(a), vb = _mm256_set1_epi8(b),
                vc = _mm256_set1_epi8(c), vd = _mm256_set1_epi8(d);
  for (; i + 32 <= len; i += 32) {
    const __m256i x =
        _mm256_loadu_si256(reinterpret_cast<const __m256i*>(buf + i));
    ca += __builtin_popcount(
        static_cast<uint32_t>(_mm256_movemask_epi8(_mm256_cmpeq_epi8(x, va))));
    cb += __builtin_popcount(
        static_cast<uint32_t>(_mm256_movemask_epi8(_mm256_cmpeq_epi8(x, vb))));
    cc += __builtin_popcount(
        static_cast<uint32_t>(_mm256_movemask_epi8(_mm256_cmpeq_epi8(x, vc))));
    cd += __builtin_popcount(
        static_cast<uint32_t>(_mm256_movemask_epi8(_mm256_cmpeq_epi8(x, vd))));
  }
#endif
  for (; i < len; ++i) {
    ca += buf[i] == a;
    cb += buf[i] == b;
    cc += buf[i] == c;
    cd += buf[i] == d;
  }
  out[0] = ca;
  out[1] = cb;
  out[2] = cc;
  out[3] = cd;
}

// libsvm: "label k:v k:v ...". Labels <= 0 -> 0, > 0 -> 1. Slot = 0.
//
// Sentinel-scanning single pass: requires the buffer to END with a line
// terminator (returns -6 otherwise; parse_chunk appends '\n'). Every
// whitespace/number run then provably stops at the final '\n'/'\r', so
// the hot loops carry no per-byte end compares and no per-line memchr —
// worth ~1.3x over the bounded two-pass shape at CTR entry sizes.
// (With AVX2 the structural-scan parser below replaces this path
// entirely; this scalar body is the portable fallback.)
int ps_parse_libsvm_scalar(const char* buf, int64_t len,
                    int64_t max_rows, int64_t max_nnz,
                    float* labels, int64_t* row_splits,  // size max_rows+1
                    uint64_t* keys, float* vals, uint64_t* slots,
                    int64_t* out_rows, int64_t* out_nnz, int64_t* err_line) {
  const char* p = buf;
  const char* end = buf + len;
  int64_t rows = 0, nnz = 0, line = 0;
  row_splits[0] = 0;
  if (len <= 0) {
    *out_rows = 0;
    *out_nnz = 0;
    return 0;
  }
  if (end[-1] != '\n' && end[-1] != '\r') return -6;  // sentinel contract
  while (p < end) {
    skip_ws_nl(p);
    if (*p == '\n') {  // blank line
      ++p;
      ++line;
      continue;
    }
    if (*p == '\r') {
      p += (p + 1 < end && p[1] == '\n') ? 2 : 1;
      ++line;
      continue;
    }
    if (rows >= max_rows) return -1;
    double y = parse_float_nl(p, end);
    labels[rows] = y > 0 ? 1.0f : 0.0f;
    while (true) {
      skip_ws_nl(p);
      if (*p == '\n') {
        ++p;
        break;
      }
      if (*p == '\r') {
        p += (p + 1 < end && p[1] == '\n') ? 2 : 1;
        break;
      }
      uint64_t k;
      if (!parse_u64_nl(p, end, k)) {
        *err_line = line;
        return -2;
      }
      float v = 1.0f;
      if (*p == ':') {
        ++p;
        // empty value ("k:" then whitespace/EOL) means 1.0, like the Python
        // parser; never let strtod skip leading whitespace across the EOL
        if (*p != ' ' && *p != '\t' && *p != '\n' && *p != '\r') {
          v = static_cast<float>(parse_float_nl(p, end));
        }
      }
      if (nnz >= max_nnz) return -1;
      keys[nnz] = k;
      vals[nnz] = v;
      if (slots) slots[nnz] = 0;  // null for slotless callers
      ++nnz;
    }
    ++rows;
    row_splits[rows] = nnz;
    ++line;
  }
  *out_rows = rows;
  *out_nnz = nnz;
  return 0;
}

int ps_parse_libsvm(const char* buf, int64_t len,
                    int64_t max_rows, int64_t max_nnz,
                    float* labels, int64_t* row_splits,
                    uint64_t* keys, float* vals, uint64_t* slots,
                    int64_t* out_rows, int64_t* out_nnz, int64_t* err_line) {
#if defined(__AVX2__)
  return ps_parse_libsvm_simd(buf, len, max_rows, max_nnz, labels,
                              row_splits, keys, vals, slots, out_rows,
                              out_nnz, err_line);
#else
  return ps_parse_libsvm_scalar(buf, len, max_rows, max_nnz, labels,
                                row_splits, keys, vals, slots, out_rows,
                                out_nnz, err_line);
#endif
}

// criteo TSV: label \t 13 ints \t 26 hex cats. Missing fields skipped.
// Integer column j -> key j, slot j+1, value sign*log1p(|x|);
// categorical column j -> key hex id, slot j+14, value 1.0.
int ps_parse_criteo(const char* buf, int64_t len,
                    int64_t max_rows, int64_t max_nnz,
                    float* labels, int64_t* row_splits,
                    uint64_t* keys, float* vals, uint64_t* slots,
                    int64_t* out_rows, int64_t* out_nnz, int64_t* err_line) {
  (void)err_line;  // criteo skips malformed lines instead of erroring
  const char* p = buf;
  const char* end = buf + len;
  const bool any_cr = chunk_has_cr(buf, len);
  int64_t rows = 0, nnz = 0, line = 0;
  row_splits[0] = 0;
  while (p < end) {
    const char* next_line;
    const char* line_end = find_line_end(p, end, &next_line, any_cr);
    if (p >= line_end) {
      p = next_line;
      ++line;
      continue;
    }
    // count fields first: need 40 columns; otherwise skip the line.
    // memchr hops tab-to-tab at SIMD speed instead of testing every byte
    int cols = 1;
    for (const char* q = p; q < line_end; ++q) {
      q = static_cast<const char*>(memchr(q, '\t', line_end - q));
      if (!q) break;
      ++cols;
    }
    if (cols < 40) {
      p = next_line;
      ++line;
      continue;
    }
    if (rows >= max_rows) return -1;
    labels[rows] = (*p == '1' && (p + 1 == line_end || p[1] == '\t')) ? 1.0f : 0.0f;
    const char* f = static_cast<const char*>(memchr(p, '\t', line_end - p));
    int col = 0;  // 0-based among the 39 feature columns
    while (f && col < 39) {
      ++f;  // past the tab
      const char* fe = static_cast<const char*>(memchr(f, '\t', line_end - f));
      const char* field_end = fe ? fe : line_end;
      if (field_end > f) {  // non-empty
        if (nnz >= max_nnz) return -1;
        if (col < 13) {
          const char* fp = f;
          bool neg = (*fp == '-');
          if (neg) ++fp;
          uint64_t x;
          // require the WHOLE field to parse: junk like "3x7" is skipped,
          // never truncated to a prefix (both ingest paths agree on this)
          if (parse_u64(fp, field_end, x) && fp == field_end) {
            double lx = std::log1p(static_cast<double>(x));
            keys[nnz] = static_cast<uint64_t>(col);
            vals[nnz] = static_cast<float>(neg ? -lx : lx);
            slots[nnz] = static_cast<uint64_t>(col + 1);
            ++nnz;
          }
        } else {
          uint64_t h = 0;
          bool ok = false;
#if defined(__AVX2__)
          // real criteo cat ids are 8 hex chars (16 tolerated); the
          // 8-byte loads cover exactly the field bytes, so no overread.
          // Other lengths (and junk) take the per-char fallback
          const int64_t flen = field_end - f;
          if (flen == 8) {
            uint32_t v32;
            if (hex8(f, v32)) {
              h = v32;
              ok = true;
            }
          } else if (flen == 16) {
            uint32_t hi32, lo32;
            if (hex8(f, hi32) && hex8(f + 8, lo32)) {
              h = (static_cast<uint64_t>(hi32) << 32) | lo32;
              ok = true;
            }
          }
#endif
          if (!ok) {
            const char* fp = f;
            ok = parse_hex64(fp, field_end, h) && fp == field_end;
          }
          if (ok) {
            keys[nnz] = h;
            vals[nnz] = 1.0f;
            slots[nnz] = static_cast<uint64_t>(col - 13 + 14);
            ++nnz;
          }
        }
      }
      ++col;
      f = fe;
    }
    ++rows;
    row_splits[rows] = nnz;
    p = next_line;
    ++line;
  }
  *out_rows = rows;
  *out_nnz = nnz;
  return 0;
}

// Hash + localize kernel (ref: src/app/linear_method/localizer.h — remap
// touched keys to dense local ids; the per-batch hot loop after parsing).
// Reproduces utils/hashing.hash_keys + np.unique(return_inverse) exactly:
// splitmix64 with slot salt into [1, num_keys), then SORTED unique keys +
// 0-based inverse ids. Runs with the GIL released (ctypes), so the
// prefetch pipeline's builder threads scale across cores — numpy's
// unique/hash hold the GIL and serialize them.
//
// identity != 0 skips hashing: gid = raw + 1 (the exact-parity key mode).
// Sorting: 2-pass LSD radix over the high 32 bits of (gid<<32 | idx),
// which requires gid to fit 32 bits (num_keys <= 2^32 — practically
// always). Return codes: 0 success; -3 identity gid outside
// [1, num_keys); -4 alloc failure; -5 num_keys > 2^32. On -3/-5 the
// caller falls back to the numpy path (which owns the error text for -3
// and handles arbitrarily large key spaces for -5).

static inline uint64_t sm64_mix(uint64_t x) {
  // identical constants/steps to utils/hashing.splitmix64 (which adds C1
  // as its first step)
  uint64_t z = x + 0x9E3779B97F4A7C15ull;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
  return z ^ (z >> 31);
}

int ps_hash_localize(const uint64_t* raw, const uint64_t* slots, int64_t n,
                     uint64_t num_keys, int identity,
                     int64_t* out_unique, int32_t* out_inverse,
                     int64_t* out_nuniq) {
  if (n == 0) {
    *out_nuniq = 0;
    return 0;
  }
  uint64_t* packed =
      static_cast<uint64_t*>(std::malloc(2 * sizeof(uint64_t) * n));
  if (!packed) return -4;
  uint64_t* alt = packed + n;
  const uint64_t usable = num_keys - 1;  // hashed gids land in [1, num_keys)
  const uint64_t C1 = 0x9E3779B97F4A7C15ull;
  if (identity) {
    for (int64_t i = 0; i < n; ++i) {
      uint64_t gid = raw[i] + 1;
      if (gid >= num_keys || gid == 0) {
        std::free(packed);
        return -3;
      }
      packed[i] = (gid << 32) | static_cast<uint64_t>(i);
    }
  } else if (slots) {
    for (int64_t i = 0; i < n; ++i) {
      uint64_t gid = sm64_mix(raw[i] ^ sm64_mix(slots[i] + C1)) % usable + 1;
      packed[i] = (gid << 32) | static_cast<uint64_t>(i);
    }
  } else {
    const uint64_t salt0 = sm64_mix(C1);  // slot 0 salt, hoisted
    for (int64_t i = 0; i < n; ++i) {
      uint64_t gid = sm64_mix(raw[i] ^ salt0) % usable + 1;
      packed[i] = (gid << 32) | static_cast<uint64_t>(i);
    }
  }
  if (num_keys <= (1ull << 32) && n < (int64_t(1) << 32)) {
    // stable LSD radix over gid bits only (low idx bits untouched, so
    // equal gids keep insertion order, like a stable sort). The count
    // table lives on the heap: builder threads may carry small stacks
    // (512 KB default pthread stacks on some platforms).
    int64_t* count =
        static_cast<int64_t*>(std::malloc(65537 * sizeof(int64_t)));
    if (!count) {
      std::free(packed < alt ? packed : alt);
      return -4;
    }
    for (int pass = 0; pass < 2; ++pass) {
      int shift = 32 + 16 * pass;
      std::memset(count, 0, 65537 * sizeof(int64_t));
      for (int64_t i = 0; i < n; ++i)
        ++count[((packed[i] >> shift) & 0xffff) + 1];
      for (int b = 0; b < 65536; ++b) count[b + 1] += count[b];
      for (int64_t i = 0; i < n; ++i)
        alt[count[(packed[i] >> shift) & 0xffff]++] = packed[i];
      uint64_t* t = packed;
      packed = alt;
      alt = t;
    }
    std::free(count);
  } else {
    // gid may exceed 32 bits: the (gid<<32 | idx) pack is lossy there
    std::free(packed);
    return -5;  // caller falls back to numpy (num_keys > 2^32)
  }
  int64_t u = 0;
  uint64_t prev = ~0ull;
  for (int64_t i = 0; i < n; ++i) {
    uint64_t gid = packed[i] >> 32;
    uint32_t idx = static_cast<uint32_t>(packed[i]);
    if (gid != prev) {
      out_unique[u++] = static_cast<int64_t>(gid);
      prev = gid;
    }
    out_inverse[idx] = static_cast<int32_t>(u - 1);
  }
  *out_nuniq = u;
  // note: `packed` here may be the original malloc block or its second
  // half; free the block start
  std::free(packed < alt ? packed : alt);
  return 0;
}

// adfea: "line_id label fea:grp fea:grp ...". Pure one-hot ad features:
// value is implicitly 1.0, the group id is the slot. Leading line id is
// metadata and dropped WITHOUT being parsed (ids like hashes are fine,
// matching the Python path). A token without ':' gets slot 0.
int ps_parse_adfea(const char* buf, int64_t len,
                   int64_t max_rows, int64_t max_nnz,
                   float* labels, int64_t* row_splits,
                   uint64_t* keys, float* vals, uint64_t* slots,
                   int64_t* out_rows, int64_t* out_nnz, int64_t* err_line) {
  const char* p = buf;
  const char* end = buf + len;
  const bool any_cr = chunk_has_cr(buf, len);
  int64_t rows = 0, nnz = 0, line = 0;
  row_splits[0] = 0;
  while (p < end) {
    const char* next_line;
    const char* line_end = find_line_end(p, end, &next_line, any_cr);
    skip_ws(p, line_end);
    if (p >= line_end) {  // blank line
      p = next_line;
      ++line;
      continue;
    }
    if (rows >= max_rows) return -1;
    while (p < line_end && *p != ' ' && *p != '\t') ++p;  // drop line id token
    skip_ws(p, line_end);
    if (p >= line_end) {  // line id but no label: skip, like the Python path
      p = next_line;
      ++line;
      continue;
    }
    // label must be a full float token (Python float() raises on junk)
    const char* tok = p;
    double y = parse_float(p, line_end);
    if (p == tok || (p < line_end && *p != ' ' && *p != '\t')) {
      *err_line = line;
      return -2;
    }
    labels[rows] = y > 0 ? 1.0f : 0.0f;
    while (true) {
      skip_ws(p, line_end);
      if (p >= line_end) break;
      uint64_t k;
      if (!parse_u64(p, line_end, k)) {
        *err_line = line;
        return -2;
      }
      uint64_t g = 0;
      if (p < line_end && *p == ':') {
        ++p;
        // "k:" with empty group -> slot 0, like Python's `if g:` guard
        if (p < line_end && *p != ' ' && *p != '\t' &&
            !parse_u64(p, line_end, g)) {
          *err_line = line;
          return -2;
        }
      }
      if (nnz >= max_nnz) return -1;
      keys[nnz] = k;
      vals[nnz] = 1.0f;
      slots[nnz] = g;
      ++nnz;
    }
    ++rows;
    row_splits[rows] = nnz;
    p = next_line;
    ++line;
  }
  *out_rows = rows;
  *out_nnz = nnz;
  return 0;
}

}  // extern "C"
