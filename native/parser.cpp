// Native text parsers: libsvm + criteo -> flat CSR arrays.
//
// Reference analog: src/data/text_parser.cc (the reference parses libsvm /
// criteo / adfea into slot-based Example protos in C++; parsing is a real
// hot path at CTR scale). This extension keeps that path native: it turns a
// chunk of complete text lines into flat (labels, row_splits, keys, vals,
// slots) arrays consumed zero-copy by numpy via ctypes.
//
// Contract notes:
//  - Caller passes a buffer of COMPLETE lines (the Python wrapper carries
//    partial tails between chunks).
//  - Outputs are caller-allocated; capacities passed in. Return value is 0
//    on success, -1 on capacity overflow, -2 on parse error (err_line gets
//    the 0-based index of the offending line in the chunk).
//  - Key hashing stays on the numpy side (utils.hashing) so Python and C++
//    ingest agree bit-for-bit by construction.

#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {

// fast positive-integer / hex parse; returns false on junk
inline bool parse_u64(const char*& p, const char* end, uint64_t& out) {
  if (p >= end || !std::isdigit(static_cast<unsigned char>(*p))) return false;
  uint64_t v = 0;
  while (p < end && std::isdigit(static_cast<unsigned char>(*p))) {
    v = v * 10 + static_cast<uint64_t>(*p - '0');
    ++p;
  }
  out = v;
  return true;
}

inline bool parse_hex64(const char*& p, const char* end, uint64_t& out) {
  uint64_t v = 0;
  const char* start = p;
  while (p < end) {
    char c = *p;
    int d;
    if (c >= '0' && c <= '9') d = c - '0';
    else if (c >= 'a' && c <= 'f') d = c - 'a' + 10;
    else if (c >= 'A' && c <= 'F') d = c - 'A' + 10;
    else break;
    v = (v << 4) | static_cast<uint64_t>(d);
    ++p;
  }
  if (p == start) return false;
  out = v;
  return true;
}

inline double parse_float(const char*& p, const char* end) {
  // strtod needs a NUL-terminated-ish region; lines are short, copy-free use
  // is fine because strtod stops at the first invalid char and the buffer
  // always ends with '\n' (guaranteed by the wrapper).
  char* q = nullptr;
  double v = std::strtod(p, &q);
  p = (q && q <= end) ? q : p;
  return v;
}

inline void skip_ws(const char*& p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t')) ++p;
}

// Line end for [p, buf_end): first '\n', '\r', or '\r\n' terminator (or
// buf_end), universal-newlines style, so CRLF and lone-CR files parse like
// the Python text-mode readers.
inline const char* find_line_end(const char* p, const char* end,
                                 const char** next_line) {
  const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
  // search '\r' only up to nl: scanning to end on every LF-only line would
  // make parsing quadratic in the chunk size
  const char* cr_stop = nl ? nl : end;
  const char* cr = static_cast<const char*>(memchr(p, '\r', cr_stop - p));
  if (cr) {
    *next_line = (cr + 1 < end && cr[1] == '\n') ? cr + 2 : cr + 1;
    return cr;
  }
  *next_line = nl ? nl + 1 : end + 1;
  return nl ? nl : end;
}

}  // namespace

extern "C" {

// libsvm: "label k:v k:v ...". Labels <= 0 -> 0, > 0 -> 1. Slot = 0.
int ps_parse_libsvm(const char* buf, int64_t len,
                    int64_t max_rows, int64_t max_nnz,
                    float* labels, int64_t* row_splits,  // size max_rows+1
                    uint64_t* keys, float* vals, uint64_t* slots,
                    int64_t* out_rows, int64_t* out_nnz, int64_t* err_line) {
  const char* p = buf;
  const char* end = buf + len;
  int64_t rows = 0, nnz = 0, line = 0;
  row_splits[0] = 0;
  while (p < end) {
    const char* next_line;
    const char* line_end = find_line_end(p, end, &next_line);
    skip_ws(p, line_end);
    if (p >= line_end) {  // blank line
      p = next_line;
      ++line;
      continue;
    }
    if (rows >= max_rows) return -1;
    double y = parse_float(p, line_end);
    labels[rows] = y > 0 ? 1.0f : 0.0f;
    while (true) {
      skip_ws(p, line_end);
      if (p >= line_end) break;
      uint64_t k;
      if (!parse_u64(p, line_end, k)) {
        *err_line = line;
        return -2;
      }
      float v = 1.0f;
      if (p < line_end && *p == ':') {
        ++p;
        // empty value ("k:" then whitespace/EOL) means 1.0, like the Python
        // parser; never let strtod skip leading whitespace across the EOL
        if (p < line_end && *p != ' ' && *p != '\t') {
          v = static_cast<float>(parse_float(p, line_end));
        }
      }
      if (nnz >= max_nnz) return -1;
      keys[nnz] = k;
      vals[nnz] = v;
      slots[nnz] = 0;
      ++nnz;
    }
    ++rows;
    row_splits[rows] = nnz;
    p = next_line;
    ++line;
  }
  *out_rows = rows;
  *out_nnz = nnz;
  return 0;
}

// criteo TSV: label \t 13 ints \t 26 hex cats. Missing fields skipped.
// Integer column j -> key j, slot j+1, value sign*log1p(|x|);
// categorical column j -> key hex id, slot j+14, value 1.0.
int ps_parse_criteo(const char* buf, int64_t len,
                    int64_t max_rows, int64_t max_nnz,
                    float* labels, int64_t* row_splits,
                    uint64_t* keys, float* vals, uint64_t* slots,
                    int64_t* out_rows, int64_t* out_nnz, int64_t* err_line) {
  (void)err_line;  // criteo skips malformed lines instead of erroring
  const char* p = buf;
  const char* end = buf + len;
  int64_t rows = 0, nnz = 0, line = 0;
  row_splits[0] = 0;
  while (p < end) {
    const char* next_line;
    const char* line_end = find_line_end(p, end, &next_line);
    if (p >= line_end) {
      p = next_line;
      ++line;
      continue;
    }
    // count fields first: need 40 columns; otherwise skip the line
    int cols = 1;
    for (const char* q = p; q < line_end; ++q)
      if (*q == '\t') ++cols;
    if (cols < 40) {
      p = next_line;
      ++line;
      continue;
    }
    if (rows >= max_rows) return -1;
    labels[rows] = (*p == '1' && (p + 1 == line_end || p[1] == '\t')) ? 1.0f : 0.0f;
    const char* f = static_cast<const char*>(memchr(p, '\t', line_end - p));
    int col = 0;  // 0-based among the 39 feature columns
    while (f && col < 39) {
      ++f;  // past the tab
      const char* fe = static_cast<const char*>(memchr(f, '\t', line_end - f));
      const char* field_end = fe ? fe : line_end;
      if (field_end > f) {  // non-empty
        if (nnz >= max_nnz) return -1;
        if (col < 13) {
          const char* fp = f;
          bool neg = (*fp == '-');
          if (neg) ++fp;
          uint64_t x;
          // require the WHOLE field to parse: junk like "3x7" is skipped,
          // never truncated to a prefix (both ingest paths agree on this)
          if (parse_u64(fp, field_end, x) && fp == field_end) {
            double lx = std::log1p(static_cast<double>(x));
            keys[nnz] = static_cast<uint64_t>(col);
            vals[nnz] = static_cast<float>(neg ? -lx : lx);
            slots[nnz] = static_cast<uint64_t>(col + 1);
            ++nnz;
          }
        } else {
          const char* fp = f;
          uint64_t h;
          if (parse_hex64(fp, field_end, h) && fp == field_end) {
            keys[nnz] = h;
            vals[nnz] = 1.0f;
            slots[nnz] = static_cast<uint64_t>(col - 13 + 14);
            ++nnz;
          }
        }
      }
      ++col;
      f = fe;
    }
    ++rows;
    row_splits[rows] = nnz;
    p = next_line;
    ++line;
  }
  *out_rows = rows;
  *out_nnz = nnz;
  return 0;
}

// adfea: "line_id label fea:grp fea:grp ...". Pure one-hot ad features:
// value is implicitly 1.0, the group id is the slot. Leading line id is
// metadata and dropped WITHOUT being parsed (ids like hashes are fine,
// matching the Python path). A token without ':' gets slot 0.
int ps_parse_adfea(const char* buf, int64_t len,
                   int64_t max_rows, int64_t max_nnz,
                   float* labels, int64_t* row_splits,
                   uint64_t* keys, float* vals, uint64_t* slots,
                   int64_t* out_rows, int64_t* out_nnz, int64_t* err_line) {
  const char* p = buf;
  const char* end = buf + len;
  int64_t rows = 0, nnz = 0, line = 0;
  row_splits[0] = 0;
  while (p < end) {
    const char* next_line;
    const char* line_end = find_line_end(p, end, &next_line);
    skip_ws(p, line_end);
    if (p >= line_end) {  // blank line
      p = next_line;
      ++line;
      continue;
    }
    if (rows >= max_rows) return -1;
    while (p < line_end && *p != ' ' && *p != '\t') ++p;  // drop line id token
    skip_ws(p, line_end);
    if (p >= line_end) {  // line id but no label: skip, like the Python path
      p = next_line;
      ++line;
      continue;
    }
    // label must be a full float token (Python float() raises on junk)
    const char* tok = p;
    double y = parse_float(p, line_end);
    if (p == tok || (p < line_end && *p != ' ' && *p != '\t')) {
      *err_line = line;
      return -2;
    }
    labels[rows] = y > 0 ? 1.0f : 0.0f;
    while (true) {
      skip_ws(p, line_end);
      if (p >= line_end) break;
      uint64_t k;
      if (!parse_u64(p, line_end, k)) {
        *err_line = line;
        return -2;
      }
      uint64_t g = 0;
      if (p < line_end && *p == ':') {
        ++p;
        // "k:" with empty group -> slot 0, like Python's `if g:` guard
        if (p < line_end && *p != ' ' && *p != '\t' &&
            !parse_u64(p, line_end, g)) {
          *err_line = line;
          return -2;
        }
      }
      if (nnz >= max_nnz) return -1;
      keys[nnz] = k;
      vals[nnz] = 1.0f;
      slots[nnz] = g;
      ++nnz;
    }
    ++rows;
    row_splits[rows] = nnz;
    p = next_line;
    ++line;
  }
  *out_rows = rows;
  *out_nnz = nnz;
  return 0;
}

}  // extern "C"
